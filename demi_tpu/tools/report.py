"""Experiment report: one markdown artifact summarizing a saved run.

The reference scatters its outputs across printMinimizationStats
(RunnerUtils.scala:1200-1266), minimization_stats.json graphs, and
experiment-dir files; this collects a saved experiment into a single
readable report — violation, external program vs MCS, per-stage
minimization table, and the artifact inventory.

    python -m demi_tpu report --app raft --nodes 3 -e exp/ [-o report.md]
"""

from __future__ import annotations

import json
import os
from typing import List, Optional


def _load(directory: str, name: str):
    path = os.path.join(directory, name)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def render_report(directory: str, app=None) -> str:
    meta = _load(directory, "metadata.json") or {}
    violation = _load(directory, "violation.json")
    externals = _load(directory, "externals.json") or []
    mcs = _load(directory, "mcs.json")
    stats = _load(directory, "minimization_stats.json")
    trace = _load(directory, "event_trace.json")
    min_trace = _load(directory, "minimized_trace.json")

    lines: List[str] = [f"# Experiment report: `{directory}`", ""]
    if meta:
        lines += [
            f"- app: **{meta.get('app', '?')}**",
            f"- saved: {meta.get('timestamp', '?')} on {meta.get('host', '?')} "
            f"(git {meta.get('git_sha', '?')[:9]})",
        ]
    if violation is not None:
        lines += ["", "## Violation", "", f"```\n{json.dumps(violation)}\n```"]

    def _count_events(t):
        if not t:
            return None
        events = t.get("events", t) if isinstance(t, dict) else t
        return len(events)

    lines += ["", "## Minimization", ""]
    rows = [("original externals", len(externals), _count_events(trace))]
    if mcs is not None:
        rows.append(("MCS externals", len(mcs), _count_events(min_trace)))
    lines.append("| stage | externals | trace events |")
    lines.append("|---|---|---|")
    for name, ext, deliv in rows:
        lines.append(f"| {name} | {ext} | {deliv if deliv is not None else '—'} |")
    if mcs is not None and externals:
        factor = len(externals) / max(1, len(mcs))
        lines.append(f"\nExternal reduction: **{len(externals)} → {len(mcs)}** "
                     f"({factor:.1f}×)")

    if stats:
        # Either a bare stage list or {"stages": [...]}.
        stages = stats if isinstance(stats, list) else stats.get("stages", [])
        if stages:
            lines += ["", "### Pipeline stages", "",
                      "| strategy | oracle | trials | prune s | replay s |",
                      "|---|---|---|---|---|"]
            total = 0
            for st in stages:
                total += st.get("total_replays", 0)
                lines.append(
                    "| {strategy} | {oracle} | {total_replays} | "
                    "{prune_duration_seconds:.2f} | "
                    "{replay_duration_seconds:.2f} |".format(
                        **{
                            "strategy": st.get("strategy", "?"),
                            "oracle": st.get("oracle", "?"),
                            "total_replays": st.get("total_replays", 0),
                            "prune_duration_seconds": st.get(
                                "prune_duration_seconds", 0.0
                            ),
                            "replay_duration_seconds": st.get(
                                "replay_duration_seconds", 0.0
                            ),
                        }
                    )
                )
            lines.append(f"\nTotal oracle trials: **{total}**")

    obs_snap = _load(directory, "obs_snapshot.json")
    if obs_snap:
        lines += ["", "## Telemetry", ""]
        # Autotune decisions first (tune.* gauges): when the run adjusted
        # its own knobs — fuzzer weights, DPOR budgets, sweep shapes —
        # the report must lead with what was chosen, not bury it in the
        # generic gauge table below.
        tune_gauges = {
            name: series
            for name, series in obs_snap.get("gauges", {}).items()
            if name.startswith("tune.")
        }
        if tune_gauges:
            lines += ["### Tuning decisions", ""]
            for name in sorted(tune_gauges):
                for key, v in sorted(tune_gauges[name].items()):
                    label = f" {key}" if key else ""
                    lines.append(f"- `{name}`{label} = {v}")
            lines.append("")
        # Prefix-fork summary (fork.* counters + the dpor.prefix_group_size
        # histogram): when the run forked lane batches off trunk snapshots,
        # say how much prefix work it skipped — next to the tuning
        # decisions, since the bucket granularity is a future tuner knob.
        counters = obs_snap.get("counters", {})
        hists = obs_snap.get("histograms", {})
        fork_counters = {
            name: series
            for name, series in counters.items()
            if name.startswith("fork.")
        }
        fork_hists = {
            name: series
            for name, series in hists.items()
            if name in ("fork.group_size", "dpor.prefix_group_size")
        }
        if fork_counters or fork_hists:
            lines += ["### Prefix-fork", ""]
            for name in sorted(fork_counters):
                for key, v in sorted(fork_counters[name].items()):
                    label = f" {key}" if key else ""
                    lines.append(f"- `{name}`{label} = {v:g}")
            for name in sorted(fork_hists):
                for key, rec in sorted(fork_hists[name].items()):
                    label = f" {key}" if key else ""
                    if rec["count"]:
                        avg = rec["sum"] / rec["count"]
                        lines.append(
                            f"- `{name}`{label}: {rec['count']} groups, "
                            f"mean size {avg:.1f}, max {rec['max']:g}"
                        )
                    else:
                        lines.append(f"- `{name}`{label}: 0 groups")
            lines.append("")
        # Static analysis (analysis.* counters): schedule-space pruned by
        # the static commutativity relation, and what the DEMI_SANITIZE
        # runtime sanitizer caught — replay-soundness facts that belong
        # next to the exploration-efficiency numbers, not buried in the
        # generic counter table.
        analysis_counters = {
            name: series
            for name, series in counters.items()
            if name.startswith("analysis.")
        }
        # The redundancy-ratio gauge belongs in this block too: a
        # dpor-only --sleep-sets run may prune nothing (no analysis.*
        # counters) yet still carry the ratio — mirroring the PR 5
        # guard, the block must not depend on any pipe.* series either.
        redundancy = obs_snap.get("gauges", {}).get("dpor.redundancy_ratio")
        if analysis_counters or redundancy:
            lines += ["### Static analysis", ""]
            sp = analysis_counters.get("analysis.static_pruned")
            if sp:
                total = sum(sp.values())
                lines.append(
                    f"- static-pruned racing pairs: {total:g} (provably "
                    "no-op flips skipped before backtrack derivation)"
                )
                for key, v in sorted(sp.items()):
                    lines.append(f"  - {key or '—'}: {v:g}")
            slp = analysis_counters.get("analysis.sleep_pruned")
            if slp:
                total = sum(slp.values())
                lines.append(
                    f"- sleep-pruned reversals: {total:g} (already-"
                    "reversed races: flips asleep at their branch, "
                    "redundant suffixes, and Mazurkiewicz-class "
                    "duplicates)"
                )
                for key, v in sorted(slp.items()):
                    lines.append(f"  - {key or '—'}: {v:g}")
            if redundancy:
                for key, v in sorted(redundancy.items()):
                    label = f" {key}" if key else ""
                    lines.append(
                        f"- redundancy ratio{label}: {v:g} (explored "
                        "schedules over the distinct-class lower bound; "
                        "1.0 = optimal)"
                    )
            for name, label in (
                ("analysis.sanitizer_mutations", "message mutations"),
                ("analysis.sanitizer_time_reads", "wall-clock reads"),
                ("analysis.sanitizer_random_draws", "global random draws"),
            ):
                series = analysis_counters.get(name)
                if series:
                    lines.append(
                        f"- sanitizer {label}: {sum(series.values()):g}"
                    )
                    for key, v in sorted(series.items()):
                        lines.append(f"  - {key or '—'}: {v:g}")
            lines.append("")
        # Async-minimization pipeline summary (pipe.* counters): how much
        # host planning hid under device execution, what speculation paid
        # off, and how often candidate lowering was a gather instead of a
        # full Python loop — the three levers DEMI_ASYNC_MIN pulls.
        pipe = {
            name: sum(series.values())
            for name, series in counters.items()
            if name.startswith("pipe.")
        }
        # DPOR double-buffered frontier rounds (dpor.inflight_*) and
        # prescribed-resume trunks (dpor.trunk_parent_hits) report here
        # too: they are the exploration half of the same async pipeline,
        # and a dpor-only run emits no pipe.* counters at all.
        dpor_async = {
            name: sum(series.values())
            for name, series in counters.items()
            if name.startswith("dpor.inflight_")
            or name == "dpor.trunk_parent_hits"
        }
        # Host-share split (the vectorized host path's health number):
        # per-driver host-vs-device seconds counters plus the *.host_share
        # gauges set by the DPOR frontier, sweep drivers, and replay
        # pipeline.
        host_split = {
            name: sum(series.values())
            for name, series in counters.items()
            if name in (
                "dpor.host_seconds", "dpor.device_seconds",
                "sweep.host_seconds", "sweep.device_seconds",
            )
        }
        if pipe or dpor_async or host_split:
            lines += ["### Pipeline", ""]

            def _ratio(num, den):
                return f"{num / den:.1%}" if den else "n/a"

            for driver in ("dpor", "sweep"):
                host = host_split.get(f"{driver}.host_seconds")
                dev = host_split.get(f"{driver}.device_seconds")
                if host is None and dev is None:
                    continue
                host, dev = host or 0.0, dev or 0.0
                lines.append(
                    f"- {driver} host share: {_ratio(host, host + dev)} "
                    f"({host:.2f}s host, {dev:.2f}s device-blocked)"
                )
            pipe_share = obs_snap.get("gauges", {}).get("pipe.host_share")
            if pipe_share:
                for key, v in sorted(pipe_share.items()):
                    label = f" {key}" if key else ""
                    lines.append(
                        f"- pipeline host share{label}: {v:.1%} (planning "
                        f"under device execution vs blocked harvesting)"
                    )

            if pipe:
                overlap = pipe.get("pipe.overlap_seconds", 0.0)
                wait = pipe.get("pipe.harvest_wait_seconds", 0.0)
                lines.append(
                    f"- overlap fraction: {_ratio(overlap, overlap + wait)} "
                    f"({overlap:.2f}s planned under device execution, "
                    f"{wait:.2f}s blocked harvesting)"
                )
                spec_hits = pipe.get("pipe.spec_hits", 0)
                spec_waste = pipe.get("pipe.spec_waste", 0)
                lines.append(
                    f"- speculative lanes: {pipe.get('pipe.spec_dispatched', 0):g} "
                    f"dispatched, {spec_hits:g} hits / {spec_waste:g} wasted "
                    f"({_ratio(spec_hits, spec_hits + spec_waste)} useful)"
                )
                if "pipe.spec_exec_hits" in pipe or "pipe.spec_exec_waste" in pipe:
                    lines.append(
                        f"- speculative host executions: "
                        f"{pipe.get('pipe.spec_exec_hits', 0):g} hits / "
                        f"{pipe.get('pipe.spec_exec_waste', 0):g} wasted"
                    )
                if "pipe.window_hits" in pipe or "pipe.window_waste" in pipe:
                    lines.append(
                        f"- window speculation: {pipe.get('pipe.window_hits', 0):g} "
                        f"batched trials saved a launch, "
                        f"{pipe.get('pipe.window_waste', 0):g} discarded"
                    )
                gathers = pipe.get("pipe.lower_gather", 0)
                cached = pipe.get("pipe.lower_cached", 0)
                full = pipe.get("pipe.lower_full", 0)
                lines.append(
                    f"- lowering cache: {_ratio(gathers + cached, gathers + cached + full)} "
                    f"hit rate ({gathers:g} gathers, {cached:g} cached, "
                    f"{full:g} full lowerings)"
                )
            if dpor_async:
                ifl = dpor_async.get("dpor.inflight_rounds", 0)
                ifl_hits = dpor_async.get("dpor.inflight_hits", 0)
                ifl_waste = dpor_async.get("dpor.inflight_waste", 0)
                lines.append(
                    f"- DPOR in-flight rounds: {ifl:g} dispatched, "
                    f"{ifl_hits:g} became the next round / "
                    f"{ifl_waste:g} discarded "
                    f"({_ratio(ifl_hits, ifl_hits + ifl_waste)} useful)"
                )
                if "dpor.trunk_parent_hits" in dpor_async:
                    lines.append(
                        f"- DPOR resume trunks: "
                        f"{dpor_async['dpor.trunk_parent_hits']:g} derived "
                        f"from a cached ancestor instead of a full-prefix "
                        f"replay"
                    )
            lines.append("")
        # Durability (persist.* counters, force-written so they reach
        # every snapshot): checkpoints written/restored, corruption
        # fallbacks, and what the launch supervisor absorbed — a run
        # that survived a preemption or degraded a surface must say so.
        persist = {
            name: series
            for name, series in counters.items()
            if name.startswith("persist.")
            or name in ("tune.cache_corrupt",)
        }
        if persist:
            lines += ["### Durability", ""]

            def _total(name):
                return sum(persist.get(name, {}).values())

            if "persist.snapshots_written" in persist:
                lines.append(
                    f"- checkpoints written: "
                    f"{_total('persist.snapshots_written'):g} "
                    f"({_total('persist.snapshot_bytes'):g} bytes)"
                )
            if "persist.restore_hits" in persist:
                lines.append(
                    f"- restores served: {_total('persist.restore_hits'):g}"
                )
            if "persist.corrupt_fallbacks" in persist:
                lines.append(
                    f"- corrupt snapshots degraded to a previous "
                    f"generation: {_total('persist.corrupt_fallbacks'):g}"
                )
            if "persist.preemptions_requested" in persist:
                lines.append(
                    f"- preemptions honored at a round boundary: "
                    f"{_total('persist.preemptions_requested'):g}"
                )
            if (
                "persist.launch_failures" in persist
                or "persist.launch_retries" in persist
            ):
                lines.append(
                    f"- launch failures: "
                    f"{_total('persist.launch_failures'):g} "
                    f"({_total('persist.launch_retries'):g} retried)"
                )
                for key, v in sorted(
                    persist.get("persist.launch_failures", {}).items()
                ):
                    lines.append(f"  - {key or '—'}: {v:g}")
            if "persist.degradations" in persist:
                lines.append(
                    f"- surfaces degraded to host twins: "
                    f"{_total('persist.degradations'):g}"
                )
                for key, v in sorted(
                    persist["persist.degradations"].items()
                ):
                    lines.append(f"  - {key or '—'}: {v:g}")
            if "persist.stage_corrupt" in persist:
                lines.append(
                    f"- corrupt stage checkpoints treated as absent: "
                    f"{_total('persist.stage_corrupt'):g}"
                )
            if "tune.cache_corrupt" in persist:
                lines.append(
                    f"- corrupt tuning caches degraded to empty: "
                    f"{_total('tune.cache_corrupt'):g}"
                )
            lines.append("")
        if counters:
            lines += ["| counter | series | value |", "|---|---|---|"]
            for name in sorted(counters):
                for key, v in sorted(counters[name].items()):
                    lines.append(f"| `{name}` | {key or '—'} | {v} |")
        gauges = obs_snap.get("gauges", {})
        if gauges:
            lines += ["", "| gauge | series | value |", "|---|---|---|"]
            for name in sorted(gauges):
                for key, v in sorted(gauges[name].items()):
                    lines.append(f"| `{name}` | {key or '—'} | {v} |")
        hists = obs_snap.get("histograms", {})
        if hists:
            lines += ["", "| histogram | series | count | sum (s) | max (s) |",
                      "|---|---|---|---|---|"]
            for name in sorted(hists):
                for key, rec in sorted(hists[name].items()):
                    mx = rec.get("max")
                    lines.append(
                        f"| `{name}` | {key or '—'} | {rec['count']} | "
                        f"{rec['sum']:.3f} | "
                        f"{'—' if mx is None else f'{mx:.3f}'} |"
                    )
        lines.append(
            "\nSnapshot: `obs_snapshot.json` "
            "(merge/print: `python -m demi_tpu stats -e <dir>`)."
        )

    # Continuous observability (obs/journal.py): when the experiment dir
    # was journaled (--journal / --checkpoint-dir), summarize the round
    # stream — the over-time view the exit snapshot above cannot give.
    try:
        from ..obs import journal as _journal

        jrecs = _journal.read_records(directory)
    except Exception:
        jrecs = []
    if jrecs:
        lines += ["", "## Continuous observability", ""]
        kinds: dict = {}
        for r in jrecs:
            kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"), 0) + 1
        incs = {r.get("inc", 0) for r in jrecs}
        lines.append(
            f"- journal: {len(jrecs)} records "
            f"({', '.join(f'{k}: {n}' for k, n in sorted(kinds.items()))}) "
            f"across {len(incs)} incarnation(s)"
        )
        dpor_recs = [r for r in jrecs if r.get("kind") == "dpor.round"]
        if dpor_recs:
            wall = sum(r.get("wall_s") or 0.0 for r in dpor_recs)
            host = sum(r.get("host_s") or 0.0 for r in dpor_recs)
            last = dpor_recs[-1]
            lines.append(
                f"- DPOR: {len(dpor_recs)} rounds"
                + (f", {len(dpor_recs) / wall:.2f} rounds/sec" if wall else "")
                + (f", host share {host / wall:.1%}" if wall else "")
                + f"; last frontier {last.get('frontier')}, "
                f"explored {last.get('explored')}"
            )
        lines.append(
            f"- tail live: `python -m demi_tpu top {directory}`"
        )

    inventory = sorted(
        f for f in os.listdir(directory) if os.path.isfile(
            os.path.join(directory, f)
        )
    )
    lines += ["", "## Artifacts", ""]
    for f in inventory:
        size = os.path.getsize(os.path.join(directory, f))
        lines.append(f"- `{f}` ({size} bytes)")
    lines += [
        "",
        "Export views: `python -m demi_tpu shiviz -e {d} ...` (ShiViz), "
        "`python -m demi_tpu dot -e {d} ...` (Graphviz).".format(d=directory),
    ]
    return "\n".join(lines) + "\n"
