"""Minimization-progress graphs from minimization_stats.json.

Reference: src/main/python/minimization_stats/{generate_graph.py,
combine_graphs.py} — gnuplot charts of iteration → #events. Here: CSV
for any plotting tool, an inline ASCII chart, and a rendered PNG/SVG
(``--render``; matplotlib, headless Agg backend — skipped gracefully if
matplotlib is absent).

    python -m demi_tpu.tools.stats_graph experiment_dir/ [--render [out.png]]
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

from ..minimization.stats import MinimizationStats


def progression(stats: MinimizationStats) -> List[Tuple[str, int, int]]:
    """(stage, global replay #, externals-at-that-replay) rows."""
    rows: List[Tuple[str, int, int]] = []
    offset = 0
    for stage in stats.stages:
        for replay, size in sorted(stage.iteration_size.items()):
            rows.append((stage.strategy, offset + replay, size))
        offset += stage.total_replays
    return rows


def to_csv(stats: MinimizationStats) -> str:
    lines = ["stage,replay,externals"]
    for stage, replay, size in progression(stats):
        lines.append(f"{stage},{replay},{size}")
    return "\n".join(lines) + "\n"


def ascii_chart(stats: MinimizationStats, width: int = 60) -> str:
    rows = progression(stats)
    if not rows:
        return "(no iteration data)\n"
    peak = max(size for _, _, size in rows) or 1
    out = []
    for stage, replay, size in rows:
        bar = "#" * max(1, int(width * size / peak))
        out.append(f"{replay:>5} {size:>5} {bar}  {stage}")
    return "\n".join(out) + "\n"


def render(stats: MinimizationStats, out_path: str) -> str:
    """Rendered progress plot (reference: generate_graph.py's gnuplot
    output): externals remaining vs replay #, one step-line per stage,
    stage boundaries marked. Returns the written path; raises
    ImportError when matplotlib is unavailable."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = progression(stats)
    fig, ax = plt.subplots(figsize=(8, 4.5))
    if rows:
        stages: List[str] = []
        for stage, _, _ in rows:
            if not stages or stages[-1] != stage:
                stages.append(stage)
        colors = plt.cm.tab10.colors
        seen_at = 0
        for si, stage in enumerate(stages):
            # rows are stage-ordered; take this stage's contiguous run.
            consumed = 0
            for s, _, _ in rows[seen_at:]:
                if s != stage:
                    break
                consumed += 1
            seg = [(r, sz) for _, r, sz in rows[seen_at : seen_at + consumed]]
            seen_at += consumed
            xs = [r for r, _ in seg]
            ys = [sz for _, sz in seg]
            ax.step(
                xs, ys, where="post",
                color=colors[si % len(colors)], label=stage, linewidth=2,
            )
            if si:
                ax.axvline(xs[0], color="0.85", linewidth=1, zorder=0)
        ax.legend(fontsize=8)
    ax.set_xlabel("replay #")
    ax.set_ylabel("external events remaining")
    ax.set_title("minimization progress")
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    do_render = False
    render_path = None
    if "--render" in args:
        i = args.index("--render")
        args.pop(i)
        do_render = True
        if i < len(args) and not args[i].startswith("-") and args[i].endswith(
            (".png", ".svg", ".pdf")
        ):
            render_path = args.pop(i)
    if not args:
        print(
            "usage: stats_graph <experiment-dir-or-stats.json> "
            "[--render [out.png]]"
        )
        return 2
    path = args[0]
    if os.path.isdir(path):
        path = os.path.join(path, "minimization_stats.json")
    with open(path) as f:
        stats = MinimizationStats.from_json(f.read())
    csv_path = os.path.splitext(path)[0] + ".csv"
    with open(csv_path, "w") as f:
        f.write(to_csv(stats))
    print(ascii_chart(stats), end="")
    print(f"csv written to {csv_path}")
    if do_render:
        out = render_path or os.path.splitext(path)[0] + ".png"
        try:
            print(f"plot written to {render(stats, out)}")
        except ImportError:
            print("matplotlib unavailable; skipped --render")
    return 0


if __name__ == "__main__":
    sys.exit(main())
