"""Minimization-progress graphs from minimization_stats.json.

Reference: src/main/python/minimization_stats/{generate_graph.py,
combine_graphs.py} — gnuplot charts of iteration → #events. Here: CSV for
any plotting tool plus an inline ASCII chart (no plotting deps in the
image).

    python -m demi_tpu.tools.stats_graph experiment_dir/
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

from ..minimization.stats import MinimizationStats


def progression(stats: MinimizationStats) -> List[Tuple[str, int, int]]:
    """(stage, global replay #, externals-at-that-replay) rows."""
    rows: List[Tuple[str, int, int]] = []
    offset = 0
    for stage in stats.stages:
        for replay, size in sorted(stage.iteration_size.items()):
            rows.append((stage.strategy, offset + replay, size))
        offset += stage.total_replays
    return rows


def to_csv(stats: MinimizationStats) -> str:
    lines = ["stage,replay,externals"]
    for stage, replay, size in progression(stats):
        lines.append(f"{stage},{replay},{size}")
    return "\n".join(lines) + "\n"


def ascii_chart(stats: MinimizationStats, width: int = 60) -> str:
    rows = progression(stats)
    if not rows:
        return "(no iteration data)\n"
    peak = max(size for _, _, size in rows) or 1
    out = []
    for stage, replay, size in rows:
        bar = "#" * max(1, int(width * size / peak))
        out.append(f"{replay:>5} {size:>5} {bar}  {stage}")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if not args:
        print("usage: stats_graph <experiment-dir-or-stats.json>")
        return 2
    path = args[0]
    if os.path.isdir(path):
        path = os.path.join(path, "minimization_stats.json")
    with open(path) as f:
        stats = MinimizationStats.from_json(f.read())
    csv_path = os.path.splitext(path)[0] + ".csv"
    with open(csv_path, "w") as f:
        f.write(to_csv(stats))
    print(ascii_chart(stats), end="")
    print(f"csv written to {csv_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
