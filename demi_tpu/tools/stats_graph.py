"""Progress graphs: minimization stats AND the continuous time series.

Reference: src/main/python/minimization_stats/{generate_graph.py,
combine_graphs.py} — gnuplot charts of iteration → #events. Here: CSV
for any plotting tool, an inline ASCII chart, and a rendered PNG/SVG
(``--render``; matplotlib, headless Agg backend — skipped gracefully if
matplotlib is absent).

Two input shapes, auto-detected per directory:

  - the continuous-observability exports (``journal.jsonl`` /
    ``timeseries.jsonl`` from obs/journal.py + obs/timeseries.py —
    any ``--checkpoint-dir`` or ``--journal`` run): per-round frontier /
    explored / rounds-per-sec trends;
  - ``minimization_stats.json`` (the per-experiment minimizer stats):
    iteration → externals-remaining, the original mode.

    python -m demi_tpu.tools.stats_graph experiment_dir/ [--render [out.png]]
    python -m demi_tpu.tools.stats_graph checkpoint_dir/
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

from ..minimization.stats import MinimizationStats


def progression(stats: MinimizationStats) -> List[Tuple[str, int, int]]:
    """(stage, global replay #, externals-at-that-replay) rows."""
    rows: List[Tuple[str, int, int]] = []
    offset = 0
    for stage in stats.stages:
        for replay, size in sorted(stage.iteration_size.items()):
            rows.append((stage.strategy, offset + replay, size))
        offset += stage.total_replays
    return rows


def to_csv(stats: MinimizationStats) -> str:
    lines = ["stage,replay,externals"]
    for stage, replay, size in progression(stats):
        lines.append(f"{stage},{replay},{size}")
    return "\n".join(lines) + "\n"


def ascii_chart(stats: MinimizationStats, width: int = 60) -> str:
    rows = progression(stats)
    if not rows:
        return "(no iteration data)\n"
    peak = max(size for _, _, size in rows) or 1
    out = []
    for stage, replay, size in rows:
        bar = "#" * max(1, int(width * size / peak))
        out.append(f"{replay:>5} {size:>5} {bar}  {stage}")
    return "\n".join(out) + "\n"


def render(stats: MinimizationStats, out_path: str) -> str:
    """Rendered progress plot (reference: generate_graph.py's gnuplot
    output): externals remaining vs replay #, one step-line per stage,
    stage boundaries marked. Returns the written path; raises
    ImportError when matplotlib is unavailable."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = progression(stats)
    fig, ax = plt.subplots(figsize=(8, 4.5))
    if rows:
        stages: List[str] = []
        for stage, _, _ in rows:
            if not stages or stages[-1] != stage:
                stages.append(stage)
        colors = plt.cm.tab10.colors
        seen_at = 0
        for si, stage in enumerate(stages):
            # rows are stage-ordered; take this stage's contiguous run.
            consumed = 0
            for s, _, _ in rows[seen_at:]:
                if s != stage:
                    break
                consumed += 1
            seg = [(r, sz) for _, r, sz in rows[seen_at : seen_at + consumed]]
            seen_at += consumed
            xs = [r for r, _ in seg]
            ys = [sz for _, sz in seg]
            ax.step(
                xs, ys, where="post",
                color=colors[si % len(colors)], label=stage, linewidth=2,
            )
            if si:
                ax.axvline(xs[0], color="0.85", linewidth=1, zorder=0)
        ax.legend(fontsize=8)
    ax.set_xlabel("replay #")
    ax.set_ylabel("external events remaining")
    ax.set_title("minimization progress")
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def timeseries_rows(root: str) -> List[Tuple[int, float, int, int, float]]:
    """(round, t, frontier, explored, wall_s) per journaled DPOR round —
    the continuous export's graphable core. Falls back to the flushed
    time-series rows' registry scalars when no round journal exists."""
    from ..obs import journal as _journal

    rows = [
        (
            int(r.get("round", 0)),
            float(r.get("t", 0.0)),
            int(r.get("frontier", 0)),
            int(r.get("explored", 0)),
            float(r.get("wall_s", 0.0)),
        )
        for r in _journal.read_records(root, kind="dpor.round")
    ]
    if rows:
        return rows
    from ..obs import timeseries as _ts

    out = []
    for i, row in enumerate(_ts.read_jsonl(root)):
        v = row.get("v", {})
        out.append(
            (
                i + 1,
                float(row.get("t", 0.0)),
                int(v.get("dpor.frontier_size", 0)),
                int(v.get("dpor.explored_set_size", 0)),
                0.0,
            )
        )
    return out


def timeseries_csv(rows) -> str:
    lines = ["round,t,frontier,explored,wall_s"]
    for rnd, t, frontier, explored, wall in rows:
        lines.append(f"{rnd},{t},{frontier},{explored},{wall}")
    return "\n".join(lines) + "\n"


def timeseries_ascii(rows, width: int = 60) -> str:
    if not rows:
        return "(no time-series data)\n"
    peak = max(frontier for _, _, frontier, _, _ in rows) or 1
    out = []
    for rnd, _, frontier, explored, wall in rows:
        bar = "#" * max(1, int(width * frontier / peak))
        rate = f"{1.0 / wall:6.2f}/s" if wall > 0 else "      —"
        out.append(
            f"{rnd:>5} frontier {frontier:>6} explored {explored:>6} "
            f"{rate} {bar}"
        )
    return "\n".join(out) + "\n"


def render_timeseries(rows, out_path: str) -> str:
    """Rendered round-stream plot: frontier and explored vs round (the
    same matplotlib/Agg contract as ``render``)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 4.5))
    if rows:
        xs = [r for r, _, _, _, _ in rows]
        ax.step(xs, [f for _, _, f, _, _ in rows], where="post",
                label="frontier", linewidth=2)
        ax.step(xs, [e for _, _, _, e, _ in rows], where="post",
                label="explored", linewidth=2)
        ax.legend(fontsize=8)
    ax.set_xlabel("round")
    ax.set_ylabel("prescriptions")
    ax.set_title("exploration progress (round journal)")
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def _timeseries_main(root: str, do_render: bool = False,
                     render_path=None) -> int:
    rows = timeseries_rows(root)
    csv_path = os.path.join(root, "timeseries.csv")
    with open(csv_path, "w") as f:
        f.write(timeseries_csv(rows))
    print(timeseries_ascii(rows), end="")
    print(f"csv written to {csv_path}")
    if do_render:
        out = render_path or os.path.join(root, "timeseries.png")
        try:
            print(f"plot written to {render_timeseries(rows, out)}")
        except ImportError:
            print("matplotlib unavailable; skipped --render")
    return 0


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    do_render = False
    render_path = None
    if "--render" in args:
        i = args.index("--render")
        args.pop(i)
        do_render = True
        if i < len(args) and not args[i].startswith("-") and args[i].endswith(
            (".png", ".svg", ".pdf")
        ):
            render_path = args.pop(i)
    if not args:
        print(
            "usage: stats_graph <experiment-dir-or-stats.json> "
            "[--render [out.png]]"
        )
        return 2
    path = args[0]
    if os.path.isdir(path):
        # Continuous-observability exports take precedence: any journaled
        # run (checkpoint dir or --journal dir) graphs its round stream.
        if os.path.exists(os.path.join(path, "journal.jsonl")) or (
            os.path.exists(os.path.join(path, "timeseries.jsonl"))
        ):
            return _timeseries_main(path, do_render, render_path)
        path = os.path.join(path, "minimization_stats.json")
    with open(path) as f:
        stats = MinimizationStats.from_json(f.read())
    csv_path = os.path.splitext(path)[0] + ".csv"
    with open(csv_path, "w") as f:
        f.write(to_csv(stats))
    print(ascii_chart(stats), end="")
    print(f"csv written to {csv_path}")
    if do_render:
        out = render_path or os.path.splitext(path)[0] + ".png"
        try:
            print(f"plot written to {render(stats, out)}")
        except ImportError:
            print("matplotlib unavailable; skipped --render")
    return 0


if __name__ == "__main__":
    sys.exit(main())
