"""Randomized differential soak: continuous-driver verdict parity against
the plain explore kernel, across fuzzed corpora, apps, and backends.

    python -m demi_tpu.tools.soak --seconds 600
    python -m demi_tpu.tools.soak --rounds 20 --variants xla,mesh

Each round draws a fresh fuzz corpus (app rotates raft-faults /
broadcast+WaitCondition / spark), runs it through the requested
continuous-driver variants, and asserts every per-seed (status,
violation) verdict equals the plain kernel's. Exit 0 = no divergence.
This is the long-form companion to tests/test_continuous.py (which pins
fixed corpora); round-4 runs: 70 rounds (r3 code) + 115+ rounds (r4
code) with zero divergences.
"""

from __future__ import annotations

import argparse
import sys
import time


def _family(pick: int, with_conditions: bool):
    """Shared app-family rotation for both soak modes: (app, gen_msgs,
    weights, cfg_kw, ncond). One definition so the modes cannot drift
    onto different configurations."""
    import dataclasses

    import jax.numpy as jnp

    from ..apps.broadcast import broadcast_send_generator, make_broadcast_app
    from ..apps.raft import make_raft_app, raft_send_generator
    from ..apps.spark_dag import make_spark_app, spark_send_generator
    from ..fuzzing import FuzzerWeights

    if pick == 0:
        app = make_raft_app(3, bug="multivote")
        return (
            app, raft_send_generator(app),
            FuzzerWeights(send=0.3, kill=0.1, wait_quiescence=0.3,
                          hard_kill=0.15, restart=0.15),
            dict(pool_capacity=96, max_steps=160, max_external_ops=24,
                 invariant_interval=1, timer_weight=0.1),
            0,
        )
    if pick == 1:
        app = make_broadcast_app(4, reliable=False)
        weights = FuzzerWeights(send=0.5, wait_quiescence=0.25, kill=0.1)
        ncond = 0
        if with_conditions:
            def _all0(states, alive):
                return jnp.all(~alive | ((states[:, 0] & 1) != 0))

            app = dataclasses.replace(app, conditions=(_all0,))
            weights = FuzzerWeights(send=0.5, wait_quiescence=0.15,
                                    kill=0.1, wait_condition=0.25)
            ncond = 1
        return (
            app, broadcast_send_generator(app), weights,
            dict(pool_capacity=64, max_steps=96, max_external_ops=24),
            ncond,
        )
    app = make_spark_app(num_workers=3, num_stages=2, tasks_per_stage=3,
                         bug="stale_task")
    return (
        app, spark_send_generator(app),
        FuzzerWeights(send=0.4, kill=0.1, wait_quiescence=0.3,
                      hard_kill=0.1, restart=0.1),
        dict(pool_capacity=128, max_steps=160, max_external_ops=24,
             invariant_interval=1),
        0,
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seconds", type=float, default=600.0)
    p.add_argument("--rounds", type=int, default=None,
                   help="stop after N rounds instead of --seconds")
    p.add_argument("--variants", default="xla,pallas,mesh",
                   help="comma list: xla, pallas, mesh, mesh-pallas")
    p.add_argument("--lanes", type=int, default=24)
    p.add_argument("--seed", type=int, default=20260730)
    p.add_argument(
        "--mode", default="continuous",
        choices=("continuous", "round-pin", "kill-resume",
                 "service-kill-resume"),
        help="continuous: per-seed verdict parity across continuous-driver "
             "variants; round-pin: fuzzed round-delivery lanes recorded and "
             "replayed through the sequential replay kernel "
             "(ignored_absent must be 0 — every round execution is a legal "
             "sequential schedule); kill-resume: SIGKILL a checkpointed "
             "DPOR soak mid-run and verify the resumed run converges to "
             "the uninterrupted run's violation set (bit-parity on "
             "explored/interleavings/first-found); service-kill-resume: "
             "SIGKILL a `demi_tpu serve` daemon mid-queue (two tenants' "
             "jobs in flight) and verify `serve --resume --drain` "
             "converges every tenant's artifact set exactly (no frame "
             "lost, none minimized twice)",
    )
    args = p.parse_args(argv)

    if args.mode == "round-pin":
        return _round_pin_soak(args)
    if args.mode == "kill-resume":
        return _kill_resume_soak(args)
    if args.mode == "service-kill-resume":
        return _service_kill_resume_soak(args)

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..apps.common import dsl_start_events
    from ..device import DeviceConfig, make_explore_kernel
    from ..device.continuous import ContinuousSweepDriver
    from ..device.encoding import lower_program, stack_programs
    from ..fuzzing import Fuzzer
    from ..parallel.mesh import make_mesh

    variant_kw = {
        "xla": dict(),
        "pallas": dict(impl="pallas", block_lanes=4),
        "mesh": dict(mesh=None),  # filled below (mesh built lazily)
        "mesh-pallas": dict(impl="pallas", block_lanes=1, mesh=None),
    }
    names = [v.strip() for v in args.variants.split(",") if v.strip()]
    for v in names:
        if v not in variant_kw:
            raise SystemExit(f"unknown variant {v!r}")
    if any(v.startswith("mesh") for v in names):
        mesh = make_mesh()
        for v in names:
            if v.startswith("mesh"):
                variant_kw[v]["mesh"] = mesh

    rng = np.random.RandomState(args.seed)
    rounds = 0
    t0 = time.time()
    n = args.lanes
    while True:
        if args.rounds is not None:
            if rounds >= args.rounds:
                break
        elif time.time() - t0 >= args.seconds:
            break
        rounds += 1
        app, gen_msgs, weights, cfg_kw, ncond = _family(
            rounds % 3, with_conditions=True
        )
        cfg = DeviceConfig.for_app(app, **cfg_kw)
        fz = Fuzzer(num_events=int(rng.randint(6, 12)), weights=weights,
                    message_gen=gen_msgs, prefix=dsl_start_events(app),
                    max_kills=2, wait_budget=(5, 30), num_conditions=ncond)
        base = int(rng.randint(0, 1 << 30))
        gen = lambda s: fz.generate_fuzz_test(seed=base + s)  # noqa: E731
        kernel = make_explore_kernel(app, cfg)
        progs = stack_programs(
            [lower_program(app, cfg, gen(s)) for s in range(n)]
        )
        keys = np.stack(
            [np.asarray(jax.random.PRNGKey(s)) for s in range(n)]
        )
        ref = kernel(progs, keys)
        ref_st = np.asarray(ref.status)
        ref_vio = np.asarray(ref.violation)
        for name in names:
            drv = ContinuousSweepDriver(
                app, cfg, gen, batch=8,
                seg_steps=int(rng.choice([16, 28, 32])),
                **variant_kw[name],
            )
            st, vio = drv.sweep(n)
            for s in range(n):
                if st[s] != int(ref_st[s]) or vio[s] != int(ref_vio[s]):
                    print(
                        f"DIVERGENCE round={rounds} app={app.name} "
                        f"variant={name} seed={s} base={base}: "
                        f"cont=({st[s]},{vio[s]}) "
                        f"plain=({int(ref_st[s])},{int(ref_vio[s])})",
                        flush=True,
                    )
                    return 2
        if rounds % 5 == 0:
            print(f"round {rounds} ok ({time.time() - t0:.0f}s)", flush=True)
    print(
        f"SOAK OK: {rounds} rounds, "
        f"{len(names) * n * rounds} lane-verdicts compared",
        flush=True,
    )
    return 0


def _round_pin_soak(args) -> int:
    """Round-delivery robustness: fuzzed programs over the three app
    families run as single round-mode lanes with record_trace; each
    recorded linearization replays through the SEQUENTIAL replay kernel
    and must match exactly (ignored_absent == 0, same deliveries/
    status/violation) — tests/test_rounds.py's pin, at soak scale."""
    import numpy as np

    import jax

    from ..apps.common import dsl_start_events
    from ..device import DeviceConfig
    from ..device.encoding import lower_program
    from ..device.explore import make_run_lane
    from ..device.replay import make_replay_run_lane
    from ..fuzzing import Fuzzer

    rng = np.random.RandomState(args.seed)
    rounds = 0
    checked = 0
    skipped = 0
    t0 = time.time()
    kernels = {}
    while True:
        if args.rounds is not None:
            if rounds >= args.rounds:
                break
        elif time.time() - t0 >= args.seconds:
            break
        rounds += 1
        # Conditions stay off here: the sequential replay kernel applies
        # records without consulting segment conditions, so a
        # cond-gated round lane would not be a like-for-like pin.
        app, gen_msgs, weights, cfg_kw, _nc = _family(
            rounds % 3, with_conditions=False
        )
        # One compiled kernel pair per app family (shapes are constant).
        if app.name not in kernels:
            rcfg = DeviceConfig.for_app(
                app, **{**cfg_kw, "invariant_interval": 0},
                round_delivery=True, record_trace=True,
                trace_capacity=cfg_kw["max_steps"] * 2,
            )
            pcfg = DeviceConfig.for_app(
                app,
                **{
                    **cfg_kw,
                    "invariant_interval": 0,
                    "max_steps": rcfg.trace_rows,
                    # Rounds free consumed entries before inserting, so
                    # the linearization's transient peak can exceed the
                    # round lane's by up to num_actors slots.
                    "pool_capacity": (
                        cfg_kw["pool_capacity"] + app.num_actors
                    ),
                },
            )
            kernels[app.name] = (
                rcfg,
                jax.jit(make_run_lane(app, rcfg)),
                jax.jit(make_replay_run_lane(app, pcfg)),
            )
        rcfg, run, replay = kernels[app.name]
        fz = Fuzzer(num_events=int(rng.randint(6, 12)), weights=weights,
                    message_gen=gen_msgs, prefix=dsl_start_events(app),
                    max_kills=2, wait_budget=(5, 30))
        for s in range(args.lanes):
            base = int(rng.randint(0, 1 << 30))
            prog = lower_program(app, rcfg, fz.generate_fuzz_test(seed=base))
            key = jax.random.PRNGKey(base)
            res = run(prog, key)
            tl = int(res.trace_len)
            if int(res.status) == 4 or tl > rcfg.trace_rows:
                skipped += 1  # pool/trace overflow: config, not semantics
                continue
            trace = np.asarray(res.trace)[:tl]
            rep = replay(trace, key)
            ok = (
                int(rep.ignored_absent) == 0
                and int(rep.deliveries) == int(res.deliveries)
                and int(rep.status) == int(res.status)
                and int(rep.violation) == int(res.violation)
            )
            checked += 1
            if not ok:
                print(
                    f"ROUND-PIN DIVERGENCE round={rounds} app={app.name} "
                    f"base={base}: round=({int(res.status)},"
                    f"{int(res.violation)},{int(res.deliveries)}) "
                    f"replay=({int(rep.status)},{int(rep.violation)},"
                    f"{int(rep.deliveries)},ign={int(rep.ignored_absent)})",
                    flush=True,
                )
                return 2
        if rounds % 5 == 0:
            print(
                f"round-pin {rounds} ok, {checked} lanes, "
                f"{skipped} overflow-skipped ({time.time() - t0:.0f}s)",
                flush=True,
            )
    if checked < max(1, (checked + skipped) // 2):
        # Silent coverage collapse (a family overflowing on most seeds)
        # must fail the soak, not pass vacuously — and must not log OK
        # first (exit-3 runs used to print both lines).
        print(
            f"ROUND-PIN SOAK: >50% of lanes overflow-skipped "
            f"({checked} checked, {skipped} skipped)",
            flush=True,
        )
        return 3
    print(
        f"ROUND-PIN SOAK OK: {rounds} rounds, {checked} lanes "
        f"({skipped} overflow-skipped)",
        flush=True,
    )
    return 0


def _kill_resume_soak(args) -> int:
    """Preemption-tolerance soak (demi_tpu.persist): per cycle, run one
    checkpointed DPOR search to completion (the reference), then run the
    SAME search again, SIGKILL it mid-soak — the harshest preemption:
    no handler runs, a snapshot write may be torn mid-file — and
    ``demi_tpu resume`` it to completion. The resumed run must converge
    to the uninterrupted run's results EXACTLY: same violation-code set,
    same first-found record digest, same explored count and
    interleavings (checkpoints are atomic + generation-versioned, and
    rounds are deterministic in the restored state, so kill-and-resume
    is bit-parity, not just eventual agreement). The kill delay grows
    with the cycle index so the SIGKILL lands at different phases —
    including inside checkpoint writes."""
    import json
    import os
    import shutil
    import signal
    import subprocess
    import tempfile

    cycles = args.rounds if args.rounds is not None else 3
    rounds = int(os.environ.get("DEMI_SOAK_KR_ROUNDS", "8"))
    base_cmd = [
        sys.executable, "-m", "demi_tpu", "dpor",
        "--app", "raft", "--bug", "multivote", "--nodes", "3",
        "--batch", "8", "--rounds", str(rounds), "--max-messages", "60",
        "--checkpoint-every", "1",
    ]
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"
    ))

    def summary_of(out: str):
        for line in reversed(out.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return None

    t0 = time.time()
    for cycle in range(cycles):
        if args.rounds is None and time.time() - t0 >= args.seconds:
            break
        workdir = tempfile.mkdtemp(prefix="demi_kr_")
        try:
            dir_u = os.path.join(workdir, "uninterrupted")
            dir_k = os.path.join(workdir, "killed")
            ref = subprocess.run(
                base_cmd + ["--checkpoint-dir", dir_u],
                capture_output=True, text=True, env=env, timeout=600,
            )
            want = summary_of(ref.stdout)
            if want is None:
                print(f"KILL-RESUME: no summary from reference run\n"
                      f"{ref.stdout}\n{ref.stderr}", flush=True)
                return 2
            proc = subprocess.Popen(
                base_cmd + ["--checkpoint-dir", dir_k],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            )
            # Kill once at least one complete generation exists, after a
            # cycle-dependent extra delay (land in different phases).
            deadline = time.time() + 300
            while time.time() < deadline:
                gens = [
                    e for e in (
                        os.listdir(dir_k) if os.path.isdir(dir_k) else []
                    )
                    if e.startswith("ckpt-") and not e.endswith(".tmp")
                ]
                if gens or proc.poll() is not None:
                    break
                time.sleep(0.05)
            time.sleep(0.1 * cycle)
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
            proc.communicate(timeout=60)
            res = subprocess.run(
                [sys.executable, "-m", "demi_tpu", "resume", dir_k],
                capture_output=True, text=True, env=env, timeout=600,
            )
            got = summary_of(res.stdout)
            if got is None:
                print(f"KILL-RESUME: no summary from resumed run\n"
                      f"{res.stdout}\n{res.stderr}", flush=True)
                return 2
            for key in ("violation_codes", "first_found", "explored",
                        "interleavings", "rounds_done",
                        "violation_found"):
                if want.get(key) != got.get(key):
                    print(
                        f"KILL-RESUME DIVERGENCE cycle={cycle} "
                        f"key={key}: uninterrupted={want.get(key)!r} "
                        f"resumed={got.get(key)!r}",
                        flush=True,
                    )
                    return 2
            # Journal continuity (obs/journal.py): the resumed run must
            # have continued the SAME round journal with no duplicated
            # and no missing rounds — even when the SIGKILL landed
            # between a checkpoint and later journaled rounds (resume
            # truncates those, then re-journals them).
            from ..obs import journal as _journal

            rounds = [
                r.get("round")
                for r in _journal.read_records(dir_k, "dpor.round")
            ]
            # Rotation-tolerant continuity: a long soak's journal may
            # have rotated away its oldest rounds, so require a
            # gap-free, duplicate-free run ENDING at rounds_done (a
            # fresh-start prefix of 1..N satisfies this too).
            ok = bool(rounds) and rounds == list(
                range(rounds[0], rounds[0] + len(rounds))
            )
            if not ok or rounds[-1] != got.get("rounds_done"):
                print(
                    f"KILL-RESUME JOURNAL GAP cycle={cycle}: rounds="
                    f"{rounds} rounds_done={got.get('rounds_done')}",
                    flush=True,
                )
                return 2
            print(
                f"kill-resume cycle {cycle} ok "
                f"(explored={got.get('explored')}, "
                f"codes={got.get('violation_codes')}, "
                f"{time.time() - t0:.0f}s)",
                flush=True,
            )
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
    print("KILL-RESUME SOAK OK", flush=True)
    return 0


def _service_kill_resume_soak(args) -> int:
    """Service preemption-tolerance soak (demi_tpu/service): per cycle,
    run a two-tenant job mix on an in-process service to completion
    (the reference artifact sets), then serve the SAME mix from a
    `demi_tpu serve` daemon, SIGKILL the daemon mid-queue — no handler
    runs, a checkpoint write may be torn — and `serve --resume --drain`
    it to completion. Every tenant's fetched artifact set must converge
    EXACTLY to the reference (eid-insensitive signatures): no violation
    frame lost, none minimized twice (the namespaced-queue dedup), and
    the durable per-job frame counters must agree. Runs at tiny shapes
    (DEMI_SOAK_SKR_LANES overrides)."""
    import json
    import os
    import shutil
    import signal
    import subprocess
    import tempfile

    from ..service import ExplorationService, artifact_signature

    cycles = args.rounds if args.rounds is not None else 3
    lanes = int(os.environ.get("DEMI_SOAK_SKR_LANES", "12"))
    chunk = int(os.environ.get("DEMI_SOAK_SKR_CHUNK", "8"))
    max_frames = int(os.environ.get("DEMI_SOAK_SKR_FRAMES", "2"))
    workload = {
        "app": "broadcast", "nodes": 4, "bug": "x", "num_events": 8,
        "max_messages": 96, "pool": 64,
    }
    tenants = [("acme", 0), ("umbrella", 1)]
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"
    ))
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))

    def sig_sets(frame_lists):
        return {
            name: {
                int(f["seed"]): artifact_signature(f["result"])
                for f in frames
                if f["status"] == "done"
            }
            for name, frames in frame_lists.items()
        }

    # Reference: in-process, uninterrupted.
    ref = ExplorationService(None, default_chunk=chunk)
    ref_jobs = {}
    for name, base in tenants:
        job = ref.submit(
            name, workload, lanes=lanes, chunk=chunk, base_key=base,
            max_frames=max_frames, wildcards=False,
        )
        ref_jobs[name] = job["job"]
    ref.run_until_idle()
    want = sig_sets({
        name: ref.job_frames(jid) for name, jid in ref_jobs.items()
    })
    want_counts = {
        name: ref.jobs[jid].frames_done for name, jid in ref_jobs.items()
    }

    t0 = time.time()
    for cycle in range(cycles):
        if args.rounds is None and time.time() - t0 >= args.seconds:
            break
        workdir = tempfile.mkdtemp(prefix="demi_skr_")
        try:
            state = os.path.join(workdir, "state")
            proc = subprocess.Popen(
                [sys.executable, "-m", "demi_tpu", "serve",
                 "--state-dir", state, "--chunk", str(chunk)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=repo,
            )
            addr = json.loads(proc.stdout.readline())["addr"]
            for name, base in tenants:
                sub = subprocess.run(
                    [sys.executable, "-m", "demi_tpu", "submit",
                     "--addr", addr, "--tenant", name,
                     "--app", "broadcast", "--nodes", "4", "--bug", "x",
                     "--num-events", "8", "--max-messages", "96",
                     "--pool", "64", "--lanes", str(lanes),
                     "--chunk", str(chunk), "--base-key", str(base),
                     "--max-frames", str(max_frames), "--no-wildcards"],
                    capture_output=True, text=True, env=env, timeout=180,
                    cwd=repo,
                )
                if sub.returncode != 0:
                    print(f"SERVICE-KILL-RESUME: submit failed\n"
                          f"{sub.stdout}\n{sub.stderr}", flush=True)
                    return 2
            # Kill once at least one checkpoint generation exists, plus
            # a cycle-dependent delay so the SIGKILL lands in different
            # phases (mid-sweep, mid-minimize, mid-checkpoint-write).
            deadline = time.time() + 300
            while time.time() < deadline:
                gens = [
                    e for e in (
                        os.listdir(state) if os.path.isdir(state) else []
                    )
                    if e.startswith("ckpt-") and not e.endswith(".tmp")
                ]
                if gens or proc.poll() is not None:
                    break
                time.sleep(0.05)
            time.sleep(0.2 * cycle)
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
            proc.communicate(timeout=60)
            res = subprocess.run(
                [sys.executable, "-m", "demi_tpu", "serve",
                 "--state-dir", state, "--resume", "--drain",
                 "--chunk", str(chunk)],
                capture_output=True, text=True, env=env, timeout=600,
                cwd=repo,
            )
            if res.returncode != 0:
                print(f"SERVICE-KILL-RESUME: resume failed rc="
                      f"{res.returncode}\n{res.stdout}\n{res.stderr}",
                      flush=True)
                return 2
            summary = json.loads(res.stdout.strip().splitlines()[-1])
            by_tenant = {
                j["tenant"]: j for j in summary["jobs"]
            }
            # Fetch-equivalent: the resumed daemon exited; read the
            # artifacts from its final checkpoint (the same frames a
            # `jobs --fetch` would have returned).
            from ..persist import CheckpointStore

            ckpt = CheckpointStore(state).load_latest()
            frames = ckpt.sections["service"]["queue"]["frames"]
            got_lists = {name: [] for name, _ in tenants}
            for f in frames:
                tenant = f.get("ns", "").split("/")[0]
                if tenant in got_lists:
                    got_lists[tenant].append(f)
            got = sig_sets(got_lists)
            for name, _ in tenants:
                if got.get(name) != want.get(name):
                    print(
                        f"SERVICE-KILL-RESUME DIVERGENCE cycle={cycle} "
                        f"tenant={name}: want "
                        f"{sorted(want.get(name, {}))} got "
                        f"{sorted(got.get(name, {}))}",
                        flush=True,
                    )
                    return 2
                if by_tenant[name]["frames_done"] != want_counts[name]:
                    print(
                        f"SERVICE-KILL-RESUME FRAME COUNT cycle={cycle} "
                        f"tenant={name}: want {want_counts[name]} got "
                        f"{by_tenant[name]['frames_done']} (a frame was "
                        "lost or minimized twice)",
                        flush=True,
                    )
                    return 2
            print(
                f"service-kill-resume cycle {cycle} ok "
                f"(frames={ {n: by_tenant[n]['frames_done'] for n, _ in tenants} }, "
                f"{time.time() - t0:.0f}s)",
                flush=True,
            )
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
    print("SERVICE-KILL-RESUME SOAK OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
