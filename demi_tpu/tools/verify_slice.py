"""End-to-end verification slice (SURVEY.md §7.4): device fuzz sweep →
violating lane → traced re-run → host lift (GuidedScheduler) → DDMin →
verified MCS.

Run: ``python -m demi_tpu.tools.verify_slice [--impl xla|pallas]``.
Exits nonzero if any stage fails; prints one status line per stage.

This is the smoke path the verify skill drives; it lives in-repo so it
can't rot (the /tmp copy it replaces went stale after an API change).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--impl", choices=["xla", "pallas"], default="xla")
    parser.add_argument("--lanes", type=int, default=256)
    parser.add_argument(
        "--adapter", action="store_true",
        help="run the external-app slice instead: unmodified asyncio app "
             "-> fuzz -> violation -> gamut-minimize -> strict replay",
    )
    args = parser.parse_args(argv)
    if args.adapter:
        return adapter_slice()

    import jax
    import numpy as np

    from ..apps.common import dsl_start_events, make_host_invariant
    from ..apps.raft import T_CLIENT, make_raft_app
    from ..config import SchedulerConfig
    from ..device import (
        DeviceConfig,
        make_explore_kernel,
        make_explore_kernel_pallas,
    )
    from ..device.core import ST_OVERFLOW, ST_VIOLATION
    from ..device.encoding import lower_program, stack_programs
    from ..external_events import MessageConstructor, Send, WaitQuiescence
    from ..runner import lift_lane_to_host, sts_sched_ddmin

    app = make_raft_app(3, bug="gap_append")
    cfg = DeviceConfig.for_app(
        app, pool_capacity=96, max_steps=224, max_external_ops=16,
        invariant_interval=1, timer_weight=0.05,
    )

    def cmd(node, v):
        return Send(
            app.actor_name(node),
            MessageConstructor(lambda vv=v: (T_CLIENT, 0, vv, 0, 0, 0, 0)),
        )

    program = dsl_start_events(app) + [
        WaitQuiescence(budget=40),
        cmd(0, 10), cmd(1, 11), cmd(2, 12),
        WaitQuiescence(budget=120),
    ]

    B = args.lanes
    if args.impl == "pallas":
        kernel = make_explore_kernel_pallas(app, cfg, block_lanes=64)
    else:
        kernel = make_explore_kernel(app, cfg)
    progs = stack_programs([lower_program(app, cfg, program)] * B)
    keys = jax.random.split(jax.random.PRNGKey(0), B)
    res = kernel(progs, keys)
    st = np.asarray(res.status)
    assert int((st == ST_OVERFLOW).sum()) == 0, "pool overflow: raise pool_capacity"
    lanes = np.flatnonzero(st == ST_VIOLATION)
    print(f"[1/5] {args.impl} sweep: {len(lanes)} violating of {B} lanes")
    assert len(lanes) > 0, "sweep found no violation"

    lane = int(lanes[0])
    config = SchedulerConfig(invariant_check=make_host_invariant(app))
    single, host = lift_lane_to_host(app, cfg, progs, keys, lane, config)
    assert int(single.violation) != 0, "traced re-run disagrees with sweep"
    print(f"[2/5] traced re-run: violation code {int(single.violation)}")
    assert host.violation is not None, "host lift lost the violation"
    print(f"[3/5] host lift: violation code {host.violation.code}")

    # externals=None: minimize over the lifted trace's own externals (the
    # program's objects never executed in this trace — see runner.py).
    mcs, verified = sts_sched_ddmin(config, host.trace, None, host.violation)
    kept = mcs.get_all_events()
    n_orig = len(host.trace.original_externals)
    print(f"[4/5] DDMin: {n_orig} -> {len(kept)} externals")
    assert verified is not None, "MCS failed verification"
    print("[5/5] MCS verified — SLICE OK")
    return 0


def adapter_slice() -> int:
    """External-app slice: the unmodified asyncio UDP-lock fixture under
    fuzz -> phantom-grant violation -> canonical gamut -> strict replay.
    The app-specific pieces (predicate, driver program) come from the
    fixture's integration surface (udp_lock_main.py), shared with
    tests/test_asyncio_adapter.py."""
    import os

    from ..bridge import BridgeSession, bridge_invariant
    from ..config import SchedulerConfig
    from ..runner import FuzzResult, run_the_gamut
    from ..schedulers import RandomScheduler
    from ..schedulers.replay import ReplayScheduler

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    fixtures = os.path.join(repo, "tests", "fixtures")
    sys.path.insert(0, fixtures)
    from udp_lock_main import make_program, phantom_grant

    launcher = [sys.executable, os.path.join(fixtures, "udp_lock_main.py")]
    env = {"PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}

    with BridgeSession(launcher, env=env) as session:
        print(f"[1/4] adapter registered: {', '.join(session.actor_names)}")
        config = SchedulerConfig(
            invariant_check=bridge_invariant(predicate=phantom_grant)
        )
        program = make_program(session)
        found = None
        for seed in range(40):
            r = RandomScheduler(
                config, seed=seed, max_messages=120,
                invariant_check_interval=1, timer_weight=0.4,
            ).execute(program)
            if r.violation is not None:
                found = r
                break
        assert found is not None, "phantom grant never surfaced"
        print(f"[2/4] violation {found.violation} at seed {seed}")
        gamut = run_the_gamut(
            config,
            FuzzResult(program=program, trace=found.trace,
                       violation=found.violation, executions=seed + 1),
        )
        print(
            f"[3/4] gamut: {len(program)} -> {len(gamut.mcs_externals)} "
            f"externals over {len(gamut.stages)} stages"
        )
        assert len(gamut.mcs_externals) < len(program)
        replayed = ReplayScheduler(config).replay(found.trace, program)
        assert replayed.violation is not None
        assert replayed.violation.matches(found.violation)
        print("[4/4] strict replay reproduced — ADAPTER SLICE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
