"""End-to-end verification slice (SURVEY.md §7.4): device fuzz sweep →
violating lane → traced re-run → host lift (GuidedScheduler) → DDMin →
verified MCS.

Run: ``python -m demi_tpu.tools.verify_slice [--impl xla|pallas]``.
Exits nonzero if any stage fails; prints one status line per stage.

This is the smoke path the verify skill drives; it lives in-repo so it
can't rot (the /tmp copy it replaces went stale after an API change).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--impl", choices=["xla", "pallas"], default="xla")
    parser.add_argument("--lanes", type=int, default=256)
    args = parser.parse_args(argv)

    import jax
    import numpy as np

    from ..apps.common import dsl_start_events, make_host_invariant
    from ..apps.raft import T_CLIENT, make_raft_app
    from ..config import SchedulerConfig
    from ..device import (
        DeviceConfig,
        make_explore_kernel,
        make_explore_kernel_pallas,
    )
    from ..device.core import ST_OVERFLOW, ST_VIOLATION
    from ..device.encoding import lower_program, stack_programs
    from ..external_events import MessageConstructor, Send, WaitQuiescence
    from ..runner import lift_lane_to_host, sts_sched_ddmin

    app = make_raft_app(3, bug="gap_append")
    cfg = DeviceConfig.for_app(
        app, pool_capacity=96, max_steps=224, max_external_ops=16,
        invariant_interval=1, timer_weight=0.05,
    )

    def cmd(node, v):
        return Send(
            app.actor_name(node),
            MessageConstructor(lambda vv=v: (T_CLIENT, 0, vv, 0, 0, 0, 0)),
        )

    program = dsl_start_events(app) + [
        WaitQuiescence(budget=40),
        cmd(0, 10), cmd(1, 11), cmd(2, 12),
        WaitQuiescence(budget=120),
    ]

    B = args.lanes
    if args.impl == "pallas":
        kernel = make_explore_kernel_pallas(app, cfg, block_lanes=64)
    else:
        kernel = make_explore_kernel(app, cfg)
    progs = stack_programs([lower_program(app, cfg, program)] * B)
    keys = jax.random.split(jax.random.PRNGKey(0), B)
    res = kernel(progs, keys)
    st = np.asarray(res.status)
    assert int((st == ST_OVERFLOW).sum()) == 0, "pool overflow: raise pool_capacity"
    lanes = np.flatnonzero(st == ST_VIOLATION)
    print(f"[1/5] {args.impl} sweep: {len(lanes)} violating of {B} lanes")
    assert len(lanes) > 0, "sweep found no violation"

    lane = int(lanes[0])
    config = SchedulerConfig(invariant_check=make_host_invariant(app))
    single, host = lift_lane_to_host(app, cfg, progs, keys, lane, config)
    assert int(single.violation) != 0, "traced re-run disagrees with sweep"
    print(f"[2/5] traced re-run: violation code {int(single.violation)}")
    assert host.violation is not None, "host lift lost the violation"
    print(f"[3/5] host lift: violation code {host.violation.code}")

    # externals=None: minimize over the lifted trace's own externals (the
    # program's objects never executed in this trace — see runner.py).
    mcs, verified = sts_sched_ddmin(config, host.trace, None, host.violation)
    kept = mcs.get_all_events()
    n_orig = len(host.trace.original_externals)
    print(f"[4/5] DDMin: {n_orig} -> {len(kept)} externals")
    assert verified is not None, "MCS failed verification"
    print("[5/5] MCS verified — SLICE OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
