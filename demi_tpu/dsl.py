"""The dual-tier application DSL: write an app once, run it on the host
oracle *and* inside the vmapped device kernels.

The reference tests arbitrary JVM applications by weaving interposition into
them (WeaveActor.aj). A TPU-native framework cannot interpose on arbitrary
Python, and more importantly the hot path — thousands of schedules advancing
in lockstep — requires actor handlers that XLA can trace. So in-framework
applications are written against this restricted DSL:

  - Actor state is a fixed-width ``int32[state_width]`` vector.
  - A message is a fixed-width ``int32[msg_width]`` record; ``msg[0]`` is the
    tag. On the host tier messages appear as plain int tuples.
  - The handler is a *pure, jax-traceable* function
        handler(actor_id, state, snd_id, msg) -> (state', outbox)
    with ``outbox: int32[max_outbox, 2 + msg_width]`` rows of
    ``(valid, dst, msg...)``. No Python control flow on traced values —
    use jnp.where / lax.switch.
  - Timers are self-sends whose tag is in ``timer_tags``; the runtime holds
    them as always-deliverable scheduler-controlled events (the reference
    converts JVM timers the same way, WeaveActor.aj:234-335). Delivering a
    timer consumes it; handlers re-arm by re-emitting.
  - The safety invariant is a jax-traceable predicate over all actor states
    returning an int32 violation fingerprint (0 = no violation).

The same handler drives both tiers, so host-vs-device differences isolate
engine bugs, not app bugs (the test strategy SURVEY.md §4 calls for).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

# Outbox row layout: (valid, dst, msg[0..W-1])
OUT_VALID = 0
OUT_DST = 1
OUT_MSG = 2


@dataclass(frozen=True)
class DSLApp:
    """A complete application-under-test definition."""

    name: str
    num_actors: int
    state_width: int
    msg_width: int
    max_outbox: int
    # init_state(actor_id: int) -> int32[state_width]  (static python int id)
    init_state: Callable[[int], np.ndarray]
    # handler(actor_id, state, snd_id, msg) -> (state', outbox)
    handler: Callable
    # initial_msgs(actor_id: int) -> int32[k, 2+msg_width] rows emitted at spawn
    initial_msgs: Optional[Callable[[int], np.ndarray]] = None
    # invariant(states: int32[N, S], alive: bool[N]) -> int32 fingerprint (0 = ok)
    invariant: Optional[Callable] = None
    timer_tags: Tuple[int, ...] = ()
    tag_names: Tuple[str, ...] = ()  # for pretty-printing
    # Named wait predicates (states, alive) -> bool, referenced by
    # WaitCondition(cond_id=k) — the dual-tier form of the reference's
    # host-closure WaitCondition (ExternalEventInjector.scala:541-580):
    # the same jax predicate gates injection on the host oracle and ends
    # the dispatch segment inside the device kernels.
    conditions: Tuple[Callable, ...] = ()

    # -- naming ------------------------------------------------------------
    def actor_name(self, actor_id: int) -> str:
        return f"{self.name}{actor_id}"

    def actor_id(self, name: str) -> int:
        prefix = self.name
        if not name.startswith(prefix):
            raise KeyError(name)
        return int(name[len(prefix):])

    def actor_names(self) -> Tuple[str, ...]:
        return tuple(self.actor_name(i) for i in range(self.num_actors))

    def is_timer_msg(self, msg) -> bool:
        return int(msg[0]) in self.timer_tags

    def tag_name(self, tag: int) -> str:
        if 0 <= tag < len(self.tag_names):
            return self.tag_names[tag]
        return str(tag)


# -- traced-index helpers for handlers --------------------------------------
#
# Handlers run inside the vmapped device kernels; a traced-index read/write
# (``state[i]`` / ``state.at[i].set``) there lowers to a batched gather or
# scatter, which XLA serializes on TPU (profiled at ms each inside the step
# scan — see device/ops.py). These one-hot forms are pure elementwise code.
# State/outbox vectors are narrow (tens of lanes), so the O(width) cost is
# negligible on every backend — handlers should ALWAYS use these for traced
# indices (static python-int indices are fine to index directly).

def vget(vec, i):
    """vec[i] for a traced scalar index into a 1-D vector."""
    oh = jnp.arange(vec.shape[0]) == i
    if vec.dtype == jnp.bool_:
        return jnp.any(oh & vec)
    return jnp.sum(jnp.where(oh, vec, 0))


def vset(vec, i, val, enabled=True):
    """Functional ``vec[i] = val if enabled`` for a traced scalar index."""
    oh = (jnp.arange(vec.shape[0]) == i) & enabled
    return jnp.where(oh, val, vec)


def vgather(vec, idx):
    """vec[idx] for a traced index *vector* -> same shape as ``idx``."""
    oh = idx[:, None] == jnp.arange(vec.shape[0])[None, :]
    if vec.dtype == jnp.bool_:
        return jnp.any(oh & vec[None, :], axis=1)
    return jnp.sum(jnp.where(oh, vec[None, :], 0), axis=1)


def seg_set(vec, start: int, seg):
    """Functional ``vec[start:start+len(seg)] = seg`` for a STATIC start.
    Static slice + concatenate instead of dynamic_update_slice: under vmap
    the latter lowers to scatter, which has no Mosaic lowering (pallas)."""
    return jnp.concatenate([vec[:start], seg, vec[start + seg.shape[0]:]])


def row_set(mat, i, row, enabled=True):
    """Functional ``mat[i] = row if enabled`` for a traced row index."""
    oh = (jnp.arange(mat.shape[0]) == i) & enabled
    return jnp.where(oh[:, None], row[None, :], mat)


def outbox_rows(max_outbox: int, msg_width: int, *rows: Sequence[int]) -> np.ndarray:
    """Helper for building a padded outbox array eagerly (init/initial_msgs)."""
    out = np.zeros((max_outbox, 2 + msg_width), dtype=np.int32)
    for i, row in enumerate(rows):
        out[i, OUT_VALID] = 1
        out[i, OUT_DST] = row[0]
        msg = row[1:]
        out[i, OUT_MSG : OUT_MSG + len(msg)] = msg
    return out


# Sender-id sentinel for externally injected messages (device encoding uses
# num_actors for EXTERNAL; host adapters translate).
def external_sender_id(app: DSLApp) -> int:
    return app.num_actors
