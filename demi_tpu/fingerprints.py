"""Pluggable message equivalence (fingerprinting).

Reference: src/main/scala/verification/MessageFingerprints.scala (124 LoC).
A fingerprint is any hashable value standing for "this message, up to
irrelevant detail" — replay matches deliveries by (snd, rcv, fingerprint),
and minimization clusters deliveries by fingerprint-derived logical clocks.

The device tier never calls into this module: device-DSL messages are already
fixed-width integer records whose fingerprint is the record itself (or a
masked view of it, see demi_tpu/device/encoding.py).
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Optional


class MessageFingerprinter:
    """One link in the fingerprinter chain. Return None to pass to the next.

    Also exposes the logical-clock hooks used by the ClockClusterizer
    (reference: MessageFingerprints.scala:103-123)."""

    def fingerprint(self, msg: Any) -> Optional[Any]:
        return None

    def causes_clock_increment(self, msg: Any) -> bool:
        return False

    def get_logical_clock(self, msg: Any) -> Optional[int]:
        return None


_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+|at 0x[0-9a-fA-F]+|object at .*?>")


class BaseFingerprinter(MessageFingerprinter):
    """Last-resort fingerprinter: structural for tuples/dataclasses, scrubbed
    repr otherwise (reference: BasicFingerprint regex scrub,
    MessageFingerprints.scala:39-52)."""

    def fingerprint(self, msg: Any) -> Any:
        if isinstance(msg, (int, float, str, bool, type(None), bytes)):
            return msg
        if isinstance(msg, tuple):
            return tuple(self.fingerprint(m) for m in msg)
        if hasattr(msg, "__dataclass_fields__"):
            return (type(msg).__name__,) + tuple(
                self.fingerprint(getattr(msg, f)) for f in msg.__dataclass_fields__
            )
        return _ADDR_RE.sub("<addr>", repr(msg))


class LambdaFingerprinter(MessageFingerprinter):
    def __init__(
        self,
        fingerprint_fn: Callable[[Any], Optional[Any]],
        clock_increment_fn: Optional[Callable[[Any], bool]] = None,
        logical_clock_fn: Optional[Callable[[Any], Optional[int]]] = None,
    ):
        self._fp = fingerprint_fn
        self._inc = clock_increment_fn
        self._clk = logical_clock_fn

    def fingerprint(self, msg):
        return self._fp(msg)

    def causes_clock_increment(self, msg):
        return bool(self._inc(msg)) if self._inc else False

    def get_logical_clock(self, msg):
        return self._clk(msg) if self._clk else None


class FingerprintFactory:
    """Chain of fingerprinters; app-specific first, BaseFingerprinter last.

    Reference: FingerprintFactory (MessageFingerprints.scala:83-124)."""

    def __init__(self):
        self._chain: List[MessageFingerprinter] = []
        self._base = BaseFingerprinter()

    def register(self, fp: MessageFingerprinter) -> "FingerprintFactory":
        self._chain.append(fp)
        return self

    def fingerprint(self, msg: Any) -> Any:
        for fp in self._chain:
            result = fp.fingerprint(msg)
            if result is not None:
                return result
        return self._base.fingerprint(msg)

    def causes_clock_increment(self, msg: Any) -> bool:
        return any(fp.causes_clock_increment(msg) for fp in self._chain)

    def get_logical_clock(self, msg: Any) -> Optional[int]:
        for fp in self._chain:
            clock = fp.get_logical_clock(msg)
            if clock is not None:
                return clock
        return None


def default_fingerprint_factory() -> FingerprintFactory:
    return FingerprintFactory()
