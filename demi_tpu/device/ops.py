"""Dual-mode dynamic-index primitives for the device kernels.

On TPU, XLA lowers vmapped dynamic-index gathers/scatters (``x[i]``,
``x.at[i].set``) inside a scan to serialized scatter ops in slow memory
(profiled: ~27 scatters/step at ~3-5 ms each dominated the explore step —
~130 ms/step for an 8k-lane batch, 4x slower than CPU). The same accesses
expressed as one-hot compare + where/reduce are pure elementwise/VPU code
and cost ~0.01 ms/step.

On CPU the native scatters are faster (O(1) vs O(n) work), so every helper
takes ``oh: bool`` — True selects the one-hot form. The kernels resolve the
mode once per build from ``DeviceConfig.index_mode`` ('auto' picks one-hot
exactly when the default JAX backend is a TPU).

Both modes are bit-identical by construction (tests/test_device.py parity
case runs the explore kernel in both and compares all outputs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# The one-hot forms are the SAME semantics handlers use via the dsl
# helpers — delegate so the subtle parts (bool-dtype reductions, the
# enabled-mask fold, out-of-range-drops) live in exactly one place.
from ..dsl import row_set as _row_set
from ..dsl import vgather as _vgather
from ..dsl import vget as _vget
from ..dsl import vset as _vset


def prefix_sum(x: jnp.ndarray, oh: bool) -> jnp.ndarray:
    """Inclusive prefix sum over an int vector.

    One-hot mode uses Hillis-Steele shifted adds (log2(n) pad+slice+add
    rounds): bit-identical to cumsum (integer adds are associative) while
    avoiding the ``cumsum`` primitive, which has no Mosaic lowering — this
    keeps the kernels traceable inside Pallas TPU kernels
    (device/pallas_explore.py)."""
    if not oh:
        return jnp.cumsum(x)
    n = x.shape[0]
    d = 1
    while d < n:
        x = x + jnp.pad(x[:-d], (d, 0))
        d *= 2
    return x


def rng_split(key: jnp.ndarray, n: int = 2) -> jnp.ndarray:
    """``jax.random.split`` replacement that traces to threefry2x32 +
    iota_2x32_shape instead of the opaque ``random_split`` primitive
    (unsupported by Mosaic). Bit-identical to jax.random.split for raw
    uint32 keys (verified in tests/test_pallas.py)."""
    try:
        from jax._src import prng as _prng

        return _prng.threefry_split(key, (n,))
    except (ImportError, AttributeError, TypeError):  # pragma: no cover - jax internals moved
        import jax

        return jax.random.split(key, n)


def onehot(i, n: int) -> jnp.ndarray:
    """bool[n], True at position ``i`` (all-False when i is out of range —
    the mask-style analog of a dropped scatter)."""
    return jnp.arange(n) == i


def get_scalar(vec: jnp.ndarray, i, oh: bool):
    """vec[i] with out-of-range reading as 0/False in one-hot mode."""
    if oh:
        return _vget(vec, i)
    return vec[i]


def get_row(mat: jnp.ndarray, i, oh: bool):
    """mat[i] ([n, w] -> [w]); out-of-range reads zeros in one-hot mode."""
    if oh:
        m = onehot(i, mat.shape[0])
        return jnp.sum(jnp.where(m[:, None], mat, 0), axis=0)
    return mat[i]


def set_scalar(vec: jnp.ndarray, i, val, enabled, oh: bool):
    """Functional ``vec[i] = val if enabled`` (no-op when i out of range
    in one-hot mode; scatter mode requires i in range)."""
    if oh:
        return _vset(vec, i, val, enabled)
    return vec.at[i].set(jnp.where(enabled, val, vec[i]))


def set_row(mat: jnp.ndarray, i, row, enabled, oh: bool):
    """Functional ``mat[i] = row if enabled`` for [n, w] mat."""
    if oh:
        return _row_set(mat, i, row, enabled)
    return mat.at[i].set(jnp.where(enabled, row, mat[i]))


def gather_vec(vec: jnp.ndarray, idx: jnp.ndarray, oh: bool):
    """vec[idx] for idx[k] into vec[n] -> [k]."""
    if oh:
        return _vgather(vec, idx)
    return vec[idx]


def gather_rows(mat: jnp.ndarray, idx: jnp.ndarray, oh: bool):
    """mat[idx] for idx[k] into mat[n, w] -> [k, w]."""
    if oh:
        m = (idx[:, None] == jnp.arange(mat.shape[0])[None, :]).astype(mat.dtype)
        return jnp.einsum("kn,nw->kw", m, mat)
    return mat[idx]


def gather_mat(mat: jnp.ndarray, ri: jnp.ndarray, ci: jnp.ndarray, oh: bool):
    """mat[ri, ci] for paired index vectors ri[k], ci[k] into mat[n, m]."""
    if oh:
        roh = ri[:, None] == jnp.arange(mat.shape[0])[None, :]
        coh = ci[:, None] == jnp.arange(mat.shape[1])[None, :]
        rows = jnp.einsum(
            "kn,nm->km", roh.astype(jnp.int32), mat.astype(jnp.int32)
        )
        picked = jnp.sum(jnp.where(coh, rows, 0), axis=1)
        if mat.dtype == jnp.bool_:
            return picked.astype(bool)
        return picked.astype(mat.dtype)
    return mat[ri, ci]


def pack_bits(vec: jnp.ndarray) -> jnp.ndarray:
    """bool[N] -> uint32[ceil(N/32)] little-endian bit-pack."""
    n = vec.shape[0]
    pad = (-n) % 32
    v = jnp.pad(vec, (0, pad)).reshape(-1, 32)
    return jnp.sum(
        v.astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, :],
        axis=1,
    )


def _extract_bit(words: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Select word idx>>5 from ``words`` ([W32] shared table or [P, W32]
    per-entry rows) and extract bit idx&31 -> bool[P]."""
    widx = idx >> 5
    woh = widx[:, None] == jnp.arange(words.shape[-1])[None, :]
    table = words[None, :] if words.ndim == 1 else words
    w = jnp.sum(jnp.where(woh, table, jnp.uint32(0)), axis=1)
    return ((w >> (idx & 31).astype(jnp.uint32)) & 1).astype(bool)


def packed_gather_bool(vec: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """vec[idx] for bool vec[N], idx[P] — O(P*N/32) instead of the [P, N]
    one-hot compare's O(P*N): the table packs to ceil(N/32) words, the
    per-entry word select is a tiny one-hot, and the bit extract is
    elementwise shift/mask (VPU-friendly; no dynamic gathers). Out-of-
    range idx reads False, like the one-hot form."""
    return _extract_bit(pack_bits(vec), idx)


def packed_gather_mat(
    mat: jnp.ndarray, ri: jnp.ndarray, ci: jnp.ndarray
) -> jnp.ndarray:
    """mat[ri, ci] for bool mat[N, M], paired idx vectors [P] — the
    row-word contraction is O(P*N*M/32) vs the one-hot form's O(P*N*M)
    (the dominant per-step cost at config-5 scale: P=4608, N=64 is 18.9M
    ops unpacked)."""
    packed = jax.vmap(pack_bits)(mat)  # [N, W32]
    row_words = gather_rows(packed, ri, True)  # [P, W32] one-hot form
    return _extract_bit(row_words, ci)


def first_true_index(mask: jnp.ndarray, k, oh: bool):
    """Index of the (k+1)-th True in ``mask`` (k 0-based); mask.shape[0] when
    there are fewer. The one-hot form avoids searchsorted (binary-search
    gathers serialize on TPU)."""
    cum = prefix_sum(mask.astype(jnp.int32), oh)
    if oh:
        return jnp.sum((cum < k + 1).astype(jnp.int32))
    return jnp.searchsorted(cum, k + 1, side="left").astype(jnp.int32)


def rank_slots(prefix: jnp.ndarray, want: jnp.ndarray, oh: bool):
    """For each want[i] (1-indexed rank), the first index where the
    nondecreasing ``prefix`` reaches it — vectorized searchsorted-left."""
    if oh:
        return jnp.sum(
            (prefix[None, :] < want[:, None]).astype(jnp.int32), axis=1
        )
    return jnp.searchsorted(prefix, want, side="left").astype(jnp.int32)


def scatter_rows_int(dest: jnp.ndarray, oh_kp: jnp.ndarray, rows: jnp.ndarray):
    """One-hot multi-row scatter: dest[p] = rows[k] where oh_kp[k, p]
    (at most one True per column). dest [P, W] int, rows [K, W]."""
    contrib = jnp.einsum("kp,kw->pw", oh_kp.astype(dest.dtype), rows)
    hit = jnp.any(oh_kp, axis=0)
    return jnp.where(hit[:, None], contrib, dest)


def scatter_vec_int(dest: jnp.ndarray, oh_kp: jnp.ndarray, vals: jnp.ndarray):
    """One-hot multi-element scatter into an int vector [P]."""
    contrib = jnp.einsum("kp,k->p", oh_kp.astype(dest.dtype), vals)
    hit = jnp.any(oh_kp, axis=0)
    return jnp.where(hit, contrib, dest)


def scatter_vec_bool(dest: jnp.ndarray, oh_kp: jnp.ndarray, vals: jnp.ndarray):
    """One-hot multi-element scatter into a bool vector [P]."""
    hit = jnp.any(oh_kp, axis=0)
    val = jnp.any(oh_kp & vals[:, None], axis=0)
    return jnp.where(hit, val, dest)
