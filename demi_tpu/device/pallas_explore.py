"""Pallas backend for the explore sweep: the whole step loop runs inside
one kernel, with each grid cell holding a block of lanes' full schedule
state in VMEM for the entire run.

Why: the XLA explore kernel (device/explore.py) is a `lax.while_loop`
whose carry — the complete per-lane ScheduleState — round-trips HBM every
step.  At 8k lanes the carry is tens of MB, so the loop is
HBM-bandwidth-bound even after the one-hot rewrite removed the serialized
scatters.  A Pallas kernel gridded over lane blocks keeps a block's state
resident in VMEM across all `max_steps` iterations: HBM traffic drops to
one read of the programs/keys and one write of the verdicts per lane,
regardless of step count.  This is the TPU-native answer to the
reference's per-message JVM dispatch cycle (SURVEY.md §3.1,
Instrumenter.scala:913-1109) at its hottest.

Semantics are single-source: the kernel body calls the SAME
`make_run_lane` step machinery as the XLA kernel (vmapped over the lane
block), so the two backends are bit-identical — including the
`jax.random` schedule stream, which the traced single-lane re-run
(device/explore.py make_single_lane_trace_kernel) depends on when lifting
a violating lane to the host oracle.

On non-TPU backends the kernel runs in Pallas interpret mode, which is
how the parity suite validates it on the CPU mesh (tests/test_pallas.py).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..dsl import DSLApp
from .core import DeviceConfig
from .explore import ExtProgram, LaneResult, make_run_lane


def _pad_to(x, b: int):
    """Pad axis 0 of ``x`` up to a multiple of ``b`` with zeros."""
    n = x.shape[0]
    rem = (-n) % b
    if rem == 0:
        return x
    pad = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def make_explore_kernel_pallas(
    app: DSLApp,
    cfg: DeviceConfig,
    block_lanes: int = 128,
    interpret: Optional[bool] = None,
):
    """Pallas twin of ``make_explore_kernel``: ``kernel(progs, keys) ->
    LaneResult`` with empty traces (sweeps record verdicts only; traced
    re-runs of interesting lanes use the XLA single-lane kernel).

    ``block_lanes`` sets the VMEM working set: one block's ScheduleState
    (~pool_capacity * (7 + msg_width) ints per lane) must fit. The lane
    batch is padded to a block multiple with inert all-zero programs.
    """
    if cfg.record_trace:
        raise ValueError(
            "pallas explore kernel records verdicts only; use the XLA "
            "single-lane trace kernel for trace extraction"
        )
    run_lane = make_run_lane(app, cfg)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret and not cfg.use_onehot:
        # Scatter-mode kernels trace cumsum/searchsorted/scatter, none of
        # which have Mosaic lowerings — fail fast instead of deep inside
        # the Mosaic compiler.
        raise ValueError(
            "pallas explore kernel requires the one-hot index mode on TPU "
            "(DeviceConfig(index_mode='onehot' or 'auto'))"
        )

    e, w = cfg.max_external_ops, cfg.msg_width

    # Pallas kernels may not capture constant arrays (the app's init-state
    # table, initial-message rows, timer-tag vectors...). closure_convert
    # hoists them out of the traced lane function; they become extra kernel
    # operands, broadcast to every grid cell. Bools ride as int32 (Mosaic
    # mask operands are awkward) and scalars as [1] vectors.
    def lane_block_fn(progs: ExtProgram, keys):
        return jax.vmap(run_lane)(progs, keys)

    ex_progs = ExtProgram(
        op=jax.ShapeDtypeStruct((block_lanes, e), jnp.int32),
        a=jax.ShapeDtypeStruct((block_lanes, e), jnp.int32),
        b=jax.ShapeDtypeStruct((block_lanes, e), jnp.int32),
        msg=jax.ShapeDtypeStruct((block_lanes, e, w), jnp.int32),
    )
    ex_keys = jax.ShapeDtypeStruct((block_lanes, 2), jnp.uint32)
    # jax.closure_convert hoists only inexact-dtype constants; this state
    # machine is all-integer, so hoist every const by tracing to a jaxpr
    # and threading jaxpr.consts as explicit arguments.
    closed_jaxpr, out_shape_tree = jax.make_jaxpr(
        lane_block_fn, return_shape=True
    )(ex_progs, ex_keys)
    consts = closed_jaxpr.consts
    out_treedef = jax.tree_util.tree_structure(out_shape_tree)

    def closed_fn(progs, keys, *cvals):
        flat_args = jax.tree_util.tree_leaves((progs, keys))
        out_flat = jax.core.eval_jaxpr(
            closed_jaxpr.jaxpr, cvals, *flat_args
        )
        return jax.tree_util.tree_unflatten(out_treedef, out_flat)

    def _wire(c):
        """(operand_to_pass, restore_fn) for one hoisted constant."""
        arr = jnp.asarray(c)
        restore_dtype = arr.dtype
        if arr.dtype == jnp.bool_:
            arr = arr.astype(jnp.int32)
        shaped = arr.reshape((1,)) if arr.ndim == 0 else arr
        squeeze = arr.ndim == 0

        def restore(v):
            if squeeze:
                v = v.reshape(())
            return v.astype(restore_dtype)

        return shaped, restore

    const_ops, const_restores = (
        zip(*(_wire(c) for c in consts)) if consts else ((), ())
    )

    def kernel(op_ref, a_ref, b_ref, msg_ref, key_ref, *rest):
        const_refs = rest[: len(const_ops)]
        st_ref, vio_ref, del_ref = rest[len(const_ops):]
        progs = ExtProgram(
            op=op_ref[...], a=a_ref[...], b=b_ref[...], msg=msg_ref[...]
        )
        cvals = [
            restore(ref[...])
            for ref, restore in zip(const_refs, const_restores)
        ]
        res = closed_fn(progs, key_ref[...], *cvals)
        st_ref[...] = res.status
        vio_ref[...] = res.violation
        del_ref[...] = res.deliveries

    def call(progs: ExtProgram, keys) -> LaneResult:
        n_lanes = keys.shape[0]
        op = _pad_to(jnp.asarray(progs.op, jnp.int32), block_lanes)
        a = _pad_to(jnp.asarray(progs.a, jnp.int32), block_lanes)
        b = _pad_to(jnp.asarray(progs.b, jnp.int32), block_lanes)
        msg = _pad_to(jnp.asarray(progs.msg, jnp.int32), block_lanes)
        keys_p = _pad_to(jnp.asarray(keys), block_lanes)
        padded = op.shape[0]
        grid = (padded // block_lanes,)
        lane_block = lambda i: (i, 0)
        out_shape = [
            jax.ShapeDtypeStruct((padded,), jnp.int32),  # status
            jax.ShapeDtypeStruct((padded,), jnp.int32),  # violation
            jax.ShapeDtypeStruct((padded,), jnp.int32),  # deliveries
        ]
        const_specs = [
            pl.BlockSpec(c.shape, lambda i, nd=c.ndim: (0,) * nd)
            for c in const_ops
        ]
        st, vio, dl = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_lanes, e), lane_block),
                pl.BlockSpec((block_lanes, e), lane_block),
                pl.BlockSpec((block_lanes, e), lane_block),
                pl.BlockSpec((block_lanes, e, w), lambda i: (i, 0, 0)),
                pl.BlockSpec((block_lanes, 2), lane_block),
                *const_specs,
            ],
            out_specs=[
                pl.BlockSpec((block_lanes,), lambda i: (i,)),
                pl.BlockSpec((block_lanes,), lambda i: (i,)),
                pl.BlockSpec((block_lanes,), lambda i: (i,)),
            ],
            out_shape=out_shape,
            interpret=interpret,
        )(op, a, b, msg, keys_p, *const_ops)
        empty = jnp.zeros((n_lanes, 0, 0), jnp.int32)
        return LaneResult(
            status=st[:n_lanes],
            violation=vio[:n_lanes],
            deliveries=dl[:n_lanes],
            trace=empty,
            trace_len=jnp.zeros((n_lanes,), jnp.int32),
        )

    return jax.jit(call)
