"""Pallas backends for the device kernels: the whole step loop runs inside
one kernel, with each grid cell holding a block of lanes' full schedule
state in VMEM for the entire run.

Why: the XLA kernels (device/explore.py, device/replay.py) are step loops
whose carry — the complete per-lane ScheduleState — round-trips HBM every
step.  At 8k lanes the carry is tens of MB, so the loop is
HBM-bandwidth-bound even after the one-hot rewrite removed the serialized
scatters.  A Pallas kernel gridded over lane blocks keeps a block's state
resident in VMEM across all steps: HBM traffic drops to one read of the
inputs and one write of the verdicts per lane, regardless of step count.
This is the TPU-native answer to the reference's per-message JVM dispatch
cycle (SURVEY.md §3.1, Instrumenter.scala:913-1109) at its hottest.

Semantics are single-source: the kernel bodies call the SAME
`make_run_lane` / `make_replay_run_lane` step machinery as the XLA
kernels (vmapped over the lane block), so the backends are bit-identical
— including the `jax.random` schedule stream, which the traced
single-lane re-run (device/explore.py make_single_lane_trace_kernel)
depends on when lifting a violating lane to the host oracle.

On non-TPU backends the kernels run in Pallas interpret mode, which is
how the parity suite validates them on the CPU mesh (tests/test_pallas.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..dsl import DSLApp
from .core import DeviceConfig
from .explore import ExtProgram, LaneResult, make_run_lane
from .replay import ReplayResult, make_replay_run_lane


def _pad_to(x, b: int, axis: int = 0):
    """Pad ``axis`` of ``x`` up to a multiple of ``b`` with zeros."""
    axis = axis % x.ndim
    n = x.shape[axis]
    rem = (-n) % b
    if rem == 0:
        return x
    pad = [(0, rem) if i == axis else (0, 0) for i in range(x.ndim)]
    return jnp.pad(x, pad)


def _check_pallas_cfg(cfg: DeviceConfig, interpret: Optional[bool]):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret and not cfg.use_onehot:
        # Scatter-mode kernels trace cumsum/searchsorted/scatter, none of
        # which have Mosaic lowerings — fail fast instead of deep inside
        # the Mosaic compiler.
        raise ValueError(
            "pallas kernels require the one-hot index mode on TPU "
            "(DeviceConfig(index_mode='onehot' or 'auto'))"
        )
    if not interpret and cfg.packed_gathers:
        # The packed shift/mask gathers are XLA-validated only; their
        # Mosaic lowering (uint32 shifts on padded lanes) is unproven.
        raise ValueError(
            "packed_gathers is XLA-only; drop impl='pallas' or the flag"
        )
    if not interpret and cfg.round_delivery:
        # The round step's Mosaic lowering is unvalidated (gumbel/uniform
        # sampling + 2-D record scatters); use the XLA backend for round
        # mode — its win is step-count reduction, which XLA gets too.
        raise ValueError(
            "round_delivery is XLA-only; drop impl='pallas' for round mode"
        )
    return interpret


def _make_blocked_kernel(
    block_fn,
    in_structs: Sequence[jax.ShapeDtypeStruct],
    block_lanes: int,
    interpret: bool,
    lane_dim_in: int = 0,
):
    """Generic lane-blocked pallas_call wrapper.

    ``block_fn(*block_arrays) -> tuple of arrays with leading dim
    block_lanes`` is traced once on ``in_structs`` (each with leading dim
    block_lanes); output shapes/dtypes come from the traced jaxpr.
    Every constant the trace closes over (init-state tables, timer-tag
    vectors, ...) is hoisted into an explicit kernel operand, because
    Pallas kernels may not capture constant arrays. jax.closure_convert
    only hoists inexact-dtype constants, and this state machine is
    all-integer — hence the manual jaxpr-consts threading. Bools ride as
    int32 (Mosaic mask operands are awkward) and scalars as [1] vectors.
    """
    closed_jaxpr = jax.make_jaxpr(block_fn)(*in_structs)
    consts = closed_jaxpr.consts
    out_avals = closed_jaxpr.out_avals
    for a in out_avals:
        if not a.shape or a.shape[0] != block_lanes:
            raise ValueError(
                f"block_fn outputs must have leading dim {block_lanes}, "
                f"got {a.shape}"
            )

    def _wire(c):
        """(operand_to_pass, restore_fn) for one hoisted constant."""
        arr = jnp.asarray(c)
        restore_dtype = arr.dtype
        if arr.dtype == jnp.bool_:
            arr = arr.astype(jnp.int32)
        shaped = arr.reshape((1,)) if arr.ndim == 0 else arr
        squeeze = arr.ndim == 0

        def restore(v):
            if squeeze:
                v = v.reshape(())
            return v.astype(restore_dtype)

        return shaped, restore

    const_ops, const_restores = (
        zip(*(_wire(c) for c in consts)) if consts else ((), ())
    )
    n_in = len(in_structs)

    def kernel(*refs):
        in_refs = refs[:n_in]
        const_refs = refs[n_in : n_in + len(const_ops)]
        out_refs = refs[n_in + len(const_ops):]
        cvals = [
            restore(ref[...])
            for ref, restore in zip(const_refs, const_restores)
        ]
        outs = jax.core.eval_jaxpr(
            closed_jaxpr.jaxpr, cvals, *(r[...] for r in in_refs)
        )
        for ref, val in zip(out_refs, outs):
            ref[...] = val

    def call(*arrays):
        n_lanes = arrays[0].shape[lane_dim_in]
        padded_arrays = [
            _pad_to(jnp.asarray(a), block_lanes, axis=lane_dim_in)
            for a in arrays
        ]
        padded = padded_arrays[0].shape[lane_dim_in]
        grid = (padded // block_lanes,)

        def in_spec(struct):
            nd = len(struct.shape)
            if lane_dim_in == 0:
                return pl.BlockSpec(
                    (block_lanes,) + tuple(struct.shape[1:]),
                    lambda i, nd=nd: (i,) + (0,) * (nd - 1),
                )
            return pl.BlockSpec(
                tuple(struct.shape[:-1]) + (block_lanes,),
                lambda i, nd=nd: (0,) * (nd - 1) + (i,),
            )

        def out_spec(aval):
            nd = len(aval.shape)
            return pl.BlockSpec(
                (block_lanes,) + tuple(aval.shape[1:]),
                lambda i, nd=nd: (i,) + (0,) * (nd - 1),
            )

        const_specs = [
            pl.BlockSpec(c.shape, lambda i, nd=c.ndim: (0,) * nd)
            for c in const_ops
        ]
        outs = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[in_spec(s) for s in in_structs] + const_specs,
            out_specs=[out_spec(a) for a in out_avals],
            out_shape=[
                jax.ShapeDtypeStruct((padded,) + tuple(a.shape[1:]), a.dtype)
                for a in out_avals
            ],
            interpret=interpret,
        )(*padded_arrays, *const_ops)
        return [o[:n_lanes] for o in outs]

    return call


def make_explore_kernel_pallas(
    app: DSLApp,
    cfg: DeviceConfig,
    block_lanes: int = 128,
    interpret: Optional[bool] = None,
    lane_axis: str = "leading",
):
    """Pallas twin of ``make_explore_kernel``: ``kernel(progs, keys) ->
    LaneResult`` with empty traces (sweeps record verdicts only; traced
    re-runs of interesting lanes use the XLA single-lane kernel).

    ``block_lanes`` sets the VMEM working set: one block's ScheduleState
    (~pool_capacity * (7 + msg_width) ints per lane) must fit. The lane
    batch is padded to a block multiple with inert all-zero programs.

    ``lane_axis='trailing'`` batches lanes along the LAST array axis
    inside the kernel (vmap in_axes=-1): elementwise/reduce ops then see
    [pool, lanes]-shaped data whose minor dimension is the lane block —
    the axis Mosaic vectorizes — instead of a 96-wide pool axis. Same
    results bit-for-bit; a pure layout experiment for the TPU (the
    bench matrix measures both).
    """
    if cfg.record_trace:
        raise ValueError(
            "pallas explore kernel records verdicts only; use the XLA "
            "single-lane trace kernel for trace extraction"
        )
    if lane_axis not in ("leading", "trailing"):
        raise ValueError(f"lane_axis must be leading/trailing, got {lane_axis!r}")
    interpret = _check_pallas_cfg(cfg, interpret)
    run_lane = make_run_lane(app, cfg)
    e, w = cfg.max_external_ops, cfg.msg_width
    bl = block_lanes
    trailing = lane_axis == "trailing"

    if trailing:
        def block_fn(op, a, b, msg, keys):
            res = jax.vmap(run_lane, in_axes=-1, out_axes=0)(
                ExtProgram(op=op, a=a, b=b, msg=msg), keys
            )
            return res.status, res.violation, res.deliveries, res.sched_hash

        in_structs = [
            jax.ShapeDtypeStruct((e, bl), jnp.int32),
            jax.ShapeDtypeStruct((e, bl), jnp.int32),
            jax.ShapeDtypeStruct((e, bl), jnp.int32),
            jax.ShapeDtypeStruct((e, w, bl), jnp.int32),
            jax.ShapeDtypeStruct((2, bl), jnp.uint32),
        ]
        blocked = _make_blocked_kernel(
            block_fn, in_structs, bl, interpret, lane_dim_in=-1
        )
    else:
        def block_fn(op, a, b, msg, keys):
            res = jax.vmap(run_lane)(
                ExtProgram(op=op, a=a, b=b, msg=msg), keys
            )
            return res.status, res.violation, res.deliveries, res.sched_hash

        in_structs = [
            jax.ShapeDtypeStruct((bl, e), jnp.int32),
            jax.ShapeDtypeStruct((bl, e), jnp.int32),
            jax.ShapeDtypeStruct((bl, e), jnp.int32),
            jax.ShapeDtypeStruct((bl, e, w), jnp.int32),
            jax.ShapeDtypeStruct((bl, 2), jnp.uint32),
        ]
        blocked = _make_blocked_kernel(block_fn, in_structs, bl, interpret)

    def call(progs: ExtProgram, keys) -> LaneResult:
        n_lanes = keys.shape[0]
        ins = (progs.op, progs.a, progs.b, progs.msg, keys)
        if trailing:
            ins = tuple(jnp.moveaxis(jnp.asarray(x), 0, -1) for x in ins)
        st, vio, dl, sh = blocked(*ins)
        empty = jnp.zeros((n_lanes, 0, 0), jnp.int32)
        return LaneResult(
            status=st,
            violation=vio,
            deliveries=dl,
            trace=empty,
            trace_len=jnp.zeros((n_lanes,), jnp.int32),
            sched_hash=sh,
        )

    return jax.jit(call)


def make_dpor_kernel_pallas(
    app: DSLApp,
    cfg: DeviceConfig,
    block_lanes: int = 64,
    interpret: Optional[bool] = None,
):
    """Pallas twin of ``make_dpor_kernel``: the frontier-batched DPOR
    sweep with VMEM-resident lane blocks, traces included — each lane's
    parent-tracked trace ([max_steps, rec_width]) is a kernel output, so
    the VMEM working set per lane is pool + trace (size accordingly:
    block_lanes * max_steps * rec_width * 4 bytes for the traces alone).
    """
    from .dpor_sweep import make_dpor_run_lane

    interpret = _check_pallas_cfg(cfg, interpret)
    run_lane = make_dpor_run_lane(app, cfg)
    e, w = cfg.max_external_ops, cfg.msg_width
    bl = block_lanes

    def block_fn(op, a, b, msg, prescs, keys):
        res = jax.vmap(run_lane)(
            ExtProgram(op=op, a=a, b=b, msg=msg), prescs, keys
        )
        return (
            res.status, res.violation, res.deliveries, res.trace,
            res.trace_len, res.sched_hash,
        )

    in_structs = [
        jax.ShapeDtypeStruct((bl, e), jnp.int32),
        jax.ShapeDtypeStruct((bl, e), jnp.int32),
        jax.ShapeDtypeStruct((bl, e), jnp.int32),
        jax.ShapeDtypeStruct((bl, e, w), jnp.int32),
        jax.ShapeDtypeStruct((bl, cfg.max_steps, cfg.rec_width), jnp.int32),
        jax.ShapeDtypeStruct((bl, 2), jnp.uint32),
    ]
    blocked = _make_blocked_kernel(block_fn, in_structs, bl, interpret)

    def call(progs: ExtProgram, prescs, keys) -> LaneResult:
        st, vio, dl, tr, tl, sh = blocked(
            progs.op, progs.a, progs.b, progs.msg, prescs, keys
        )
        return LaneResult(
            status=st, violation=vio, deliveries=dl, trace=tr, trace_len=tl,
            sched_hash=sh,
        )

    return jax.jit(call)


def make_replay_kernel_pallas(
    app: DSLApp,
    cfg: DeviceConfig,
    block_lanes: int = 128,
    interpret: Optional[bool] = None,
):
    """Pallas twin of ``make_replay_kernel``: ``kernel(records[B, R, W],
    keys[B]) -> ReplayResult[B]`` — the batched STS ignore-absent oracle
    with VMEM-resident lane blocks.

    The record loop always runs in the early-exit (while_loop + one-hot
    record fetch) form: the non-early-exit ``lax.scan`` over records
    slices its xs with dynamic_slice, which has no Mosaic lowering.
    Results are identical either way (the scan form is just the padded
    equivalent)."""
    if cfg.record_trace:
        raise ValueError("pallas replay kernel records verdicts only")
    interpret = _check_pallas_cfg(cfg, interpret)
    if not cfg.early_exit:
        cfg = dataclasses.replace(cfg, early_exit=True)
    run_lane = make_replay_run_lane(app, cfg)

    def _kernel_for(n_records: int):
        def block_fn(records, keys):
            res = jax.vmap(run_lane)(records, keys)
            return (
                res.status,
                res.violation,
                res.deliveries,
                res.ignored_absent,
                res.peeked,
            )

        in_structs = [
            jax.ShapeDtypeStruct(
                (block_lanes, n_records, cfg.rec_width), jnp.int32
            ),
            jax.ShapeDtypeStruct((block_lanes, 2), jnp.uint32),
        ]
        return _make_blocked_kernel(
            block_fn, in_structs, block_lanes, interpret
        )

    cache = {}

    def call(records, keys) -> ReplayResult:
        n_records = records.shape[1]
        if n_records not in cache:
            cache[n_records] = jax.jit(_kernel_for(n_records))
        st, vio, dl, ig, pk = cache[n_records](records, keys)
        return ReplayResult(
            status=st, violation=vio, deliveries=dl, ignored_absent=ig,
            peeked=pk,
        )

    return call
