"""Device-batched minimization oracles.

DDMin levels and internal-minimization rounds produce *sets* of candidate
schedules; here each set becomes one vmapped replay batch (SURVEY.md §7.2
step 6, BASELINE north star: "DDMin farms its replay-this-subsequence
trials to the same batched kernel"). Verdicts come from the jitted
invariant; only the adopted candidate is re-executed on the host oracle to
produce the bookkeeping EventTrace.

Record arrays are padded to one static shape so every round reuses the same
compiled kernel.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

import jax

from .. import obs
from ..config import SchedulerConfig
from ..dsl import DSLApp
from ..external_events import ExternalEvent
from ..minimization.test_oracle import IntViolation, TestOracle
from ..schedulers.replay import STSScheduler
from ..trace import EventTrace
from .core import DeviceConfig
from .encoding import lower_expected_trace
from .replay import make_replay_kernel


def default_device_config(
    app: DSLApp,
    trace: EventTrace,
    externals: Sequence[ExternalEvent],
    **overrides,
) -> DeviceConfig:
    """Size the static device shapes from the recorded execution: enough
    steps to replay the whole trace, enough pool for its peak concurrency
    (padded 2x for wildcard/backtrack variants), rounded up to multiples of
    8 so repeated gamut runs reuse compiled kernels."""

    def _round8(n: int) -> int:
        return max(8, (n + 7) // 8 * 8)

    n_events = len(trace.events)
    defaults = dict(
        pool_capacity=_round8(max(64, 2 * n_events)),
        max_steps=_round8(max(64, 2 * n_events)),
        max_external_ops=_round8(len(externals) + 8),
        invariant_interval=1,
        # Minimization candidates shrink far below the shared static
        # record shape; early exit makes replay wall-clock track the
        # longest live candidate instead of the shape.
        early_exit=True,
    )
    defaults.update(overrides)
    return DeviceConfig.for_app(app, **defaults)


class DeviceReplayChecker:
    """Batched candidate checking for DSL apps: lower candidate expected
    traces, replay them all at once, compare violation codes."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        config: SchedulerConfig,
        impl: Optional[str] = None,
        mesh=None,
        prefix_fork: Optional[bool] = None,
        fork_bucket: int = 8,
    ):
        self.app = app
        self.cfg = cfg
        self.config = config
        self.mesh = mesh
        # Kernel backend: 'xla' (default) or 'pallas' (VMEM-resident lane
        # blocks, device/pallas_explore.py). DEMI_DEVICE_IMPL sets the
        # default so a whole minimize pipeline can be flipped from the
        # environment for TPU experiments. A mesh shards each candidate
        # batch over its lane axis instead (one DDMin level spread across
        # chips, SURVEY.md §2.8).
        impl = impl or os.environ.get("DEMI_DEVICE_IMPL", "xla")
        if mesh is not None:
            from ..parallel.mesh import shard_replay_kernel

            if impl == "pallas":
                import sys

                print(
                    "DeviceReplayChecker: mesh sharding uses the XLA "
                    "replay kernel; ignoring impl=pallas",
                    file=sys.stderr,
                )
            self.kernel = shard_replay_kernel(app, cfg, mesh)
        elif impl == "pallas":
            from .pallas_explore import make_replay_kernel_pallas

            self.kernel = make_replay_kernel_pallas(app, cfg)
        else:
            self.kernel = make_replay_kernel(app, cfg)
        self.max_records = cfg.max_steps + cfg.max_external_ops
        # Prefix-fork (device/fork.py, DEMI_PREFIX_FORK=1 / --prefix-fork):
        # a level's candidates are identical up to the first removed index,
        # so the shared prefix is replayed ONCE on a trunk lane and each
        # first-divergence bucket forks from the (LRU-cached) snapshot —
        # verdicts stay bit-identical to scratch replay.
        from .fork import prefix_fork_enabled

        self._forker = None
        if prefix_fork_enabled(prefix_fork):
            from .fork import PrefixForker, make_replay_prefix_runner

            if impl == "pallas" and mesh is None:
                import sys

                print(
                    "DeviceReplayChecker: prefix-fork trunk/fork lanes run "
                    "on the XLA replay kernel (bit-identical verdicts)",
                    file=sys.stderr,
                )
            if mesh is not None:
                from ..parallel.mesh import shard_replay_kernel

                self._fork_kernel = shard_replay_kernel(
                    app, cfg, mesh, start_state=True
                )
            else:
                self._fork_kernel = make_replay_kernel(
                    app, cfg, start_state=True
                )
            self._forker = PrefixForker(
                make_replay_prefix_runner(app, cfg),
                bucket=fork_bucket,
                driver="replay",
            )

    @property
    def fork_stats(self) -> Optional[dict]:
        """Prefix-fork statistics (None when forking is off)."""
        return None if self._forker is None else self._forker.stats_view()

    def verdicts(
        self,
        candidates: Sequence[EventTrace],
        externals_per_candidate: Sequence[Sequence[ExternalEvent]],
        target_code: int,
    ) -> List[bool]:
        if not candidates:
            return []
        records = np.stack(
            [
                lower_expected_trace(
                    self.app, self.cfg, cand, list(ext), self.max_records
                )
                for cand, ext in zip(candidates, externals_per_candidate)
            ]
        )
        n = len(candidates)
        with obs.span(
            "device.replay_batch", candidates=n
        ) as sp:
            if self._forker is not None and n >= 2:
                codes = self._forked_codes(records, n)
            else:
                codes = self._scratch_codes(records, n)
            hits = sum(int(c) == target_code for c in codes)
            sp.set(reproductions=hits)
        if obs.enabled():
            obs.counter("device.replay.candidates").inc(n)
            obs.counter("device.replay.reproductions").inc(hits)
        return [int(c) == target_code for c in codes]

    def _scratch_codes(self, records: np.ndarray, n: int) -> np.ndarray:
        """Replay ``records`` from step 0 and return per-lane violation
        codes. Pads the batch axis to a power-of-two bucket: DDMin levels
        and removal rounds shrink the candidate count every iteration, and
        an unpadded batch would recompile the kernel per distinct size
        (profiled: a 150-delivery raft case spent ~4 min, ~100 compiles,
        in ONE internal stage). Padding rows replay candidate 0 again;
        their verdicts are sliced off."""
        bucket = max(8, 1 << (n - 1).bit_length())
        if self.mesh is not None:
            from ..parallel.mesh import pad_batch_to_devices

            bucket = pad_batch_to_devices(bucket, self.mesh)
        if bucket > n:
            records = np.concatenate(
                [records, np.repeat(records[:1], bucket - n, axis=0)]
            )
        keys = jax.random.split(jax.random.PRNGKey(0), bucket)
        res = self.kernel(records, keys)
        if obs.enabled():
            obs.counter("device.replay.pad_lanes").inc(bucket - n)
        return np.asarray(res.violation)[:n]

    def _forked_codes(self, records: np.ndarray, n: int) -> np.ndarray:
        """Prefix-fork verdicts: group candidates by bucketed shared
        prefix, replay each group's trunk once (LRU-cached across calls —
        consecutive ddmin levels and internal rounds share trunks), fork
        the lanes over the remaining suffixes. Groups too small to
        amortize a trunk fall back to the scratch kernel."""
        from .fork import padded_size

        lengths = (records[:, :, 0] != 0).sum(axis=1)
        groups, scratch = self._forker.plan(records, lengths)
        codes = np.zeros(n, np.int32)
        r = records.shape[1]
        for g in groups:
            if not self._forker.should_fork(g):
                scratch.extend(g.indices)
                continue
            p = g.prefix_len
            trunk_records = np.zeros_like(records[0])
            trunk_records[:p] = records[g.indices[0], :p]
            snap, trunk_steps, hit = self._forker.trunk(
                g.key, trunk_records, jax.random.PRNGKey(0)
            )
            suffixes = np.zeros(
                (len(g.indices), r, records.shape[2]), np.int32
            )
            suffixes[:, : r - p] = records[g.indices, p:]
            bucket = padded_size(len(g.indices), self.mesh)
            if bucket > len(g.indices):
                suffixes = np.concatenate(
                    [suffixes, np.repeat(suffixes[:1], bucket - len(g.indices), axis=0)]
                )
            keys = jax.random.split(jax.random.PRNGKey(0), bucket)
            res = self._fork_kernel(suffixes, keys, snap)
            codes[np.asarray(g.indices)] = np.asarray(res.violation)[
                : len(g.indices)
            ]
            self._forker.note_group(len(g.indices), trunk_steps, hit)
        if scratch:
            codes[np.asarray(scratch)] = self._scratch_codes(
                records[np.asarray(scratch)], len(scratch)
            )
            self._forker.note_scratch(len(scratch))
        return codes

    def host_executed_trace(
        self,
        candidate: EventTrace,
        externals: Sequence[ExternalEvent],
        violation: Any,
    ) -> Optional[EventTrace]:
        # Keep the tiers' replay power matched: when the device kernel
        # peeks (cfg.replay_peek), the host bookkeeping replay must too,
        # with the SAME prefix budget — a larger host budget would let a
        # candidate host-verify via a longer peek than the device oracle
        # that selected it allows (and vice versa on re-runs).
        sts = STSScheduler(
            self.config, candidate,
            allow_peek=self.cfg.replay_peek > 0,
            max_peek_messages=self.cfg.replay_peek,
        )
        return sts.test_with_trace(candidate, list(externals), violation)


def make_batched_internal_check(
    checker: DeviceReplayChecker,
    externals: Sequence[ExternalEvent],
    violation: IntViolation,
) -> Callable[[List[EventTrace]], List[Optional[EventTrace]]]:
    """batch_check for BatchedInternalMinimizer: device verdicts for all
    candidates, host execution only for the first reproducing one."""

    def batch_check(candidates: List[EventTrace]) -> List[Optional[EventTrace]]:
        verdicts = checker.verdicts(
            candidates, [externals] * len(candidates), violation.code
        )
        out: List[Optional[EventTrace]] = [None] * len(candidates)
        for i, ok in enumerate(verdicts):
            if ok:
                executed = checker.host_executed_trace(
                    candidates[i], externals, violation
                )
                if executed is not None:
                    out[i] = executed
                    break
        return out

    return batch_check


class DeviceSTSOracle(TestOracle):
    """TestOracle for external-event DDMin backed by the device replay
    kernel: each test() lowers the projected candidate and replays it on
    device; positives are re-executed on the host for the bookkeeping trace.
    ``test_batch`` checks a whole DDMin level at once."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        config: SchedulerConfig,
        original_trace: EventTrace,
        checker: Optional[DeviceReplayChecker] = None,
    ):
        # Pass a shared checker to reuse one compiled replay kernel across
        # pipeline stages.
        self.checker = checker or DeviceReplayChecker(app, cfg, config)
        self.original_trace = original_trace
        self.config = config

    def _project(self, externals: Sequence[ExternalEvent]) -> EventTrace:
        return (
            self.original_trace.filter_failure_detector_messages()
            .filter_checkpoint_messages()
            .subsequence_intersection(
                list(externals),
                filter_known_absents=self.config.filter_known_absents,
            )
        )

    def test(self, externals, violation_fingerprint, stats=None, init=None):
        if stats is not None:
            stats.record_replay()
        projected = self._project(externals)
        ok = self.checker.verdicts(
            [projected], [externals], violation_fingerprint.code
        )[0]
        if not ok:
            return None
        return self.checker.host_executed_trace(
            projected, externals, violation_fingerprint
        )

    def test_batch(
        self, candidates: Sequence[Sequence[ExternalEvent]], violation_fingerprint
    ) -> List[bool]:
        projected = [self._project(c) for c in candidates]
        return self.checker.verdicts(
            projected, candidates, violation_fingerprint.code
        )
