"""Device-batched minimization oracles.

DDMin levels and internal-minimization rounds produce *sets* of candidate
schedules; here each set becomes one vmapped replay batch (SURVEY.md §7.2
step 6, BASELINE north star: "DDMin farms its replay-this-subsequence
trials to the same batched kernel"). Verdicts come from the jitted
invariant; only the adopted candidate is re-executed on the host oracle to
produce the bookkeeping EventTrace.

Record arrays are padded to one static shape so every round reuses the same
compiled kernel.

Async pipeline surface (DEMI_ASYNC_MIN=1 / ``async_min=True``): the
checker adds a ``dispatch``/``harvest`` split (``PendingVerdicts`` keeps
verdict codes on device — no per-group blocking ``np.asarray``), a
``CandidateLowerer`` so a level's candidates lower as row-gathers off one
base lowering, and speculative candidate lanes riding the padded buckets:
harvested speculative codes seed a digest-keyed verdict cache the next
dispatch consumes, shrinking (or skipping) its launch. Verdicts are a
pure function of a lane's record bytes — replay lanes never consume rng —
so every async answer is bit-identical to the synchronous path's
(tests/test_async_min.py pins this).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

from .. import obs
from ..config import SchedulerConfig
from ..dsl import DSLApp
from ..external_events import ExternalEvent
from ..minimization.pipeline import (
    DEFAULT_SPECULATION_CAP,
    async_min_enabled,
    padded_bucket,
)
from ..minimization.test_oracle import IntViolation, TestOracle
from ..schedulers.replay import STSScheduler
from ..trace import EventTrace
from .core import DeviceConfig
from .encoding import CandidateLowerer, lower_expected_trace
from .replay import make_replay_kernel

#: Per-bucket-size replay key batches. Replay lanes never consume their
#: rng (injection and prescribed dispatch never split it), yet every
#: group/level used to rebuild ``jax.random.split(PRNGKey(0), bucket)``
#: from scratch — pure host churn on the minimization hot path. Bucket
#: sizes are power-of-two (plus mesh-rounded) so a handful of entries
#: serve a whole gamut run.
_REPLAY_KEYS: Dict[int, Any] = {}


def replay_keys(bucket: int):
    keys = _REPLAY_KEYS.get(bucket)
    if keys is None:
        keys = _REPLAY_KEYS[bucket] = jax.random.split(
            jax.random.PRNGKey(0), bucket
        )
    return keys


def default_device_config(
    app: DSLApp,
    trace: EventTrace,
    externals: Sequence[ExternalEvent],
    **overrides,
) -> DeviceConfig:
    """Size the static device shapes from the recorded execution: enough
    steps to replay the whole trace, enough pool for its peak concurrency
    (padded 2x for wildcard/backtrack variants), rounded up to multiples of
    8 so repeated gamut runs reuse compiled kernels."""

    def _round8(n: int) -> int:
        return max(8, (n + 7) // 8 * 8)

    n_events = len(trace.events)
    defaults = dict(
        pool_capacity=_round8(max(64, 2 * n_events)),
        max_steps=_round8(max(64, 2 * n_events)),
        max_external_ops=_round8(len(externals) + 8),
        invariant_interval=1,
        # Minimization candidates shrink far below the shared static
        # record shape; early exit makes replay wall-clock track the
        # longest live candidate instead of the shape.
        early_exit=True,
    )
    defaults.update(overrides)
    return DeviceConfig.for_app(app, **defaults)


class DeviceReplayChecker:
    """Batched candidate checking for DSL apps: lower candidate expected
    traces, replay them all at once, compare violation codes."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        config: SchedulerConfig,
        impl: Optional[str] = None,
        mesh=None,
        prefix_fork: Optional[bool] = None,
        fork_bucket: int = 8,
        async_min: Optional[bool] = None,
    ):
        self.app = app
        self.cfg = cfg
        self.config = config
        self.mesh = mesh
        # Kernel backend: 'xla' (default) or 'pallas' (VMEM-resident lane
        # blocks, device/pallas_explore.py). DEMI_DEVICE_IMPL sets the
        # default so a whole minimize pipeline can be flipped from the
        # environment for TPU experiments. A mesh shards each candidate
        # batch over its lane axis instead (one DDMin level spread across
        # chips, SURVEY.md §2.8).
        impl = impl or os.environ.get("DEMI_DEVICE_IMPL", "xla")
        # Launch-telemetry + profiler parity with the explore kernels:
        # every replay launch passes through _counted_kernel, so the
        # launch profiler (--profile-rounds on minimize) attributes
        # minimizer dispatches per shape exactly like dpor rounds.
        from .explore import _counted_kernel

        if mesh is not None:
            from ..parallel.mesh import shard_replay_kernel

            if impl == "pallas":
                import sys

                print(
                    "DeviceReplayChecker: mesh sharding uses the XLA "
                    "replay kernel; ignoring impl=pallas",
                    file=sys.stderr,
                )
            self.kernel = _counted_kernel(
                shard_replay_kernel(app, cfg, mesh), "replay-mesh"
            )
        elif impl == "pallas":
            from .pallas_explore import make_replay_kernel_pallas

            self.kernel = _counted_kernel(
                make_replay_kernel_pallas(app, cfg), "replay-pallas"
            )
        else:
            self.kernel = _counted_kernel(
                make_replay_kernel(app, cfg), "replay"
            )
        self.max_records = cfg.max_steps + cfg.max_external_ops
        # Prefix-fork (device/fork.py, DEMI_PREFIX_FORK=1 / --prefix-fork):
        # a level's candidates are identical up to the first removed index,
        # so the shared prefix is replayed ONCE on a trunk lane and each
        # first-divergence bucket forks from the (LRU-cached) snapshot —
        # verdicts stay bit-identical to scratch replay.
        from .fork import prefix_fork_enabled

        self._forker = None
        if prefix_fork_enabled(prefix_fork):
            from .fork import PrefixForker, make_replay_prefix_runner

            if impl == "pallas" and mesh is None:
                import sys

                print(
                    "DeviceReplayChecker: prefix-fork trunk/fork lanes run "
                    "on the XLA replay kernel (bit-identical verdicts)",
                    file=sys.stderr,
                )
            if mesh is not None:
                from ..parallel.mesh import shard_replay_kernel

                self._fork_kernel = _counted_kernel(
                    shard_replay_kernel(app, cfg, mesh, start_state=True),
                    "replay-fork-mesh",
                )
            else:
                self._fork_kernel = _counted_kernel(
                    make_replay_kernel(app, cfg, start_state=True),
                    "replay-fork",
                )
            from .fork import make_replay_prefix_resume_runner

            self._forker = PrefixForker(
                make_replay_prefix_runner(app, cfg),
                bucket=fork_bucket,
                driver="replay",
                # Hierarchical trunks: derive a missing trunk by resuming
                # the nearest cached ancestor over only the remaining
                # bucket rows (bit-exact vs a scratch trunk run).
                resume_runner=make_replay_prefix_resume_runner(app, cfg),
            )
        # Async minimization pipeline (DEMI_ASYNC_MIN=1 / --async-min):
        # lower-once/gather-many candidate lowering, dispatch/harvest
        # split (verdicts stay on device until harvested), speculative
        # next-level candidates riding the idle padded lanes. Verdicts
        # are a pure function of a lane's record bytes (replay never
        # consumes rng), so every async answer is bit-identical to the
        # synchronous path's.
        self._async = async_min_enabled(async_min)
        # Streaming orchestration (demi_tpu/pipeline/budget.py): when a
        # LaunchBudget is attached, every replay launch reports its lane
        # count under the "minimize" tier — the shared in-flight ledger
        # the fuzz sweep reports into as "fuzz".
        self.launch_budget = None
        self._lowerer = (
            CandidateLowerer(app, cfg, self.max_records) if self._async else None
        )
        self._spec_cache: Dict[bytes, int] = {}
        self.pipeline_stats = {
            "dispatches": 0,
            "launches": 0,
            "lanes_launched": 0,
            "spec_dispatched": 0,
            "spec_hits": 0,
            "spec_waste": 0,
            "dispatch_seconds": 0.0,
            "overlap_seconds": 0.0,
            "harvest_wait_seconds": 0.0,
        }

    @property
    def async_enabled(self) -> bool:
        return self._async

    @property
    def fork_stats(self) -> Optional[dict]:
        """Prefix-fork statistics (None when forking is off)."""
        return None if self._forker is None else self._forker.stats_view()

    def pipeline_snapshot(self) -> dict:
        """Pipeline statistics + the lowering cache's view — what bench
        config 7 and the CLI surface (None-safe: zeros when async is
        off)."""
        out = dict(self.pipeline_stats)
        if self._lowerer is not None:
            out.update(
                {f"lower_{k}": v for k, v in self._lowerer.stats.items()}
            )
            out["lowering_cache_hit_rate"] = round(
                self._lowerer.hit_rate(), 3
            )
        from ..minimization.pipeline import overlap_fraction

        spec_total = out["spec_hits"] + out["spec_waste"]
        out["spec_hit_rate"] = (
            round(out["spec_hits"] / spec_total, 3) if spec_total else 0.0
        )
        out["overlap_fraction"] = round(overlap_fraction(out), 3)
        for k in ("overlap_seconds", "harvest_wait_seconds"):
            out[k] = round(out[k], 4)
        return out

    def prime_base(
        self, trace: EventTrace, externals: Sequence[ExternalEvent]
    ) -> None:
        """Register a level/round baseline with the gather lowerer so its
        candidate subsequences lower as row-gathers. No-op when async is
        off; a base too large for the static record shape is skipped
        (its candidates full-lower — correct, just slower)."""
        if self._lowerer is not None:
            try:
                self._lowerer.register_base(trace, list(externals))
            except ValueError:
                pass

    def verdicts(
        self,
        candidates: Sequence[EventTrace],
        externals_per_candidate: Sequence[Sequence[ExternalEvent]],
        target_code: int,
    ) -> List[bool]:
        if not candidates:
            return []
        if self._async:
            # Same codes, same order — dispatch/harvest back-to-back still
            # consults the speculative verdict cache and the gather
            # lowerer, so synchronous call sites share the pipeline's
            # host-side wins.
            return self.dispatch(
                candidates, externals_per_candidate, target_code
            ).harvest()
        records = np.stack(
            [
                lower_expected_trace(
                    self.app, self.cfg, cand, list(ext), self.max_records
                )
                for cand, ext in zip(candidates, externals_per_candidate)
            ]
        )
        n = len(candidates)
        with obs.span(
            "device.replay_batch", candidates=n
        ) as sp:
            if self._forker is not None and n >= 2:
                codes = self._forked_codes(records, n)
            else:
                codes = self._scratch_codes(records, n)
            hits = sum(int(c) == target_code for c in codes)
            sp.set(reproductions=hits)
        if obs.enabled():
            obs.counter("device.replay.candidates").inc(n)
            obs.counter("device.replay.reproductions").inc(hits)
        return [int(c) == target_code for c in codes]

    # -- async pipeline: dispatch/harvest split -----------------------------

    def dispatch(
        self,
        candidates: Sequence[EventTrace],
        externals_per_candidate: Sequence[Sequence[ExternalEvent]],
        target_code: int,
        speculate: Optional[
            Sequence[Tuple[EventTrace, Sequence[ExternalEvent]]]
        ] = None,
    ) -> "PendingVerdicts":
        """Launch every candidate's replay and return WITHOUT pulling the
        verdicts off device (no blocking ``np.asarray`` — not even per
        fork group). ``speculate`` offers next-level candidates that ride
        the launches' idle padded lanes (the lanes that today replay
        duplicate rows); their harvested codes seed a digest-keyed verdict
        cache the NEXT dispatch consults, so a correct prediction turns a
        whole level into cache hits. Requires ``async_min``."""
        if not self._async:
            raise RuntimeError(
                "DeviceReplayChecker.dispatch requires async_min "
                "(DEMI_ASYNC_MIN=1 / --async-min)"
            )
        t0 = time.perf_counter()
        n = len(candidates)
        pending = PendingVerdicts(self, n, target_code)
        if n == 0:
            return pending
        self.pipeline_stats["dispatches"] += 1
        lowered = [
            self._lowerer.lower(cand, list(ext))
            for cand, ext in zip(candidates, externals_per_candidate)
        ]
        records = np.stack([r for r, _ in lowered])
        # Consume the previous launch's speculative verdicts (digest-keyed:
        # a verdict is a pure function of the record bytes). The cache is
        # single-shot — whatever this dispatch doesn't consume was a
        # misprediction and is discarded.
        consumed = set()
        for i, (_, digest) in enumerate(lowered):
            code = self._spec_cache.get(digest)
            if code is not None:
                pending.codes[i] = code
                consumed.add(digest)
        waste = len(self._spec_cache) - len(consumed)
        if self._spec_cache:
            self.pipeline_stats["spec_hits"] += len(consumed)
            self.pipeline_stats["spec_waste"] += waste
            obs.counter("pipe.spec_hits").inc(len(consumed))
            obs.counter("pipe.spec_waste").inc(waste)
            # The measured free-lane hit rate, visible to the tuner in
            # every snapshot (force_set — same contract as tune.*
            # decisions): of the speculative lanes dispatched so far,
            # the fraction whose verdicts the next level consumed.
            total = (
                self.pipeline_stats["spec_hits"]
                + self.pipeline_stats["spec_waste"]
            )
            obs.REGISTRY.gauge("pipe.spec_hit_rate").force_set(
                round(self.pipeline_stats["spec_hits"] / total, 3)
            )
        self._spec_cache = {}
        todo = [i for i in range(n) if pending.codes[i] == pending.UNRESOLVED]
        spec_pool: List[list] = []
        for strace, sext in list(speculate or [])[:DEFAULT_SPECULATION_CAP]:
            srec, sdig = self._lowerer.lower(strace, list(sext))
            spec_pool.append([sdig, srec, False])
        if todo:
            if self._forker is not None and len(todo) >= 2:
                self._dispatch_forked(pending, records, todo, spec_pool)
            else:
                self._dispatch_scratch(pending, records, todo, spec_pool)
        elif spec_pool:
            # Every candidate was a speculation hit: the level costs no
            # launch at all, and the NEXT level's speculation rides a
            # padding-only launch sized to one bucket.
            self._dispatch_scratch(pending, records, [], spec_pool)
        pending.mark_dispatched(time.perf_counter() - t0)
        return pending

    def _dispatch_scratch(
        self,
        pending: "PendingVerdicts",
        records: np.ndarray,
        idxs: List[int],
        spec_pool: List[list],
    ) -> None:
        """Scratch-replay launch for candidate positions ``idxs``, with
        speculative candidates packed into the padding lanes (leftover
        padding replays row 0, exactly like the synchronous path)."""
        rows = [records[np.asarray(idxs, np.intp)]] if idxs else []
        m = len(idxs)
        # padded_bucket is the ONE bucket formula: speculation_room's
        # free-lane estimate in minimization/pipeline.py assumes it
        # matches the dispatch-side padding exactly.
        bucket = padded_bucket(m)
        if self.mesh is not None:
            from ..parallel.mesh import pad_batch_to_devices

            bucket = pad_batch_to_devices(bucket, self.mesh)
        spec_lanes: List[Tuple[int, bytes]] = []
        fill: List[np.ndarray] = []
        for entry in spec_pool:
            if m + len(fill) >= bucket:
                break
            if entry[2]:
                continue
            entry[2] = True
            spec_lanes.append((m + len(fill), entry[0]))
            fill.append(entry[1])
        if fill:
            rows.append(np.stack(fill))
        pad = bucket - m - len(fill)
        if pad:
            first = records[idxs[0]] if idxs else (
                fill[0] if fill else records[0]
            )
            rows.append(np.repeat(first[None], pad, axis=0))
        batch = np.concatenate(rows) if len(rows) > 1 else rows[0]
        res = self.kernel(batch, replay_keys(bucket))
        self.pipeline_stats["launches"] += 1
        self.pipeline_stats["lanes_launched"] += bucket
        pending.lanes_launched += bucket
        if self.launch_budget is not None:
            self.launch_budget.note_dispatch("minimize", bucket)
        if obs.enabled():
            obs.counter("device.replay.pad_lanes").inc(pad)
        pending.add_part(
            res.violation,
            np.asarray(idxs, np.intp),
            np.arange(len(idxs), dtype=np.intp),
            spec_lanes,
        )

    def _dispatch_forked(
        self,
        pending: "PendingVerdicts",
        records: np.ndarray,
        idxs: List[int],
        spec_pool: List[list],
    ) -> None:
        """Prefix-fork launches with deferred harvest: same grouping,
        trunks (hierarchical), and fork kernels as ``_forked_codes``, but
        each group's violation vector stays on device until the pending
        handle is harvested. Speculative candidates ride a group's padding
        only when they share the group's prefix byte-exactly (their fork
        suffix is then well-defined); the rest ride the scratch launch."""
        from .fork import padded_size

        sub = records[np.asarray(idxs, np.intp)]
        lengths = (sub[:, :, 0] != 0).sum(axis=1)
        groups, scratch = self._forker.plan(sub, lengths)
        r = sub.shape[1]
        for g in groups:
            if not self._forker.should_fork(g):
                scratch.extend(g.indices)
                continue
            p = g.prefix_len
            trunk_records = np.zeros_like(sub[0])
            trunk_records[:p] = sub[g.indices[0], :p]
            snap, trunk_steps, hit = self._forker.trunk_hier(
                g.key, trunk_records, jax.random.PRNGKey(0), p
            )
            k = len(g.indices)
            suffixes = np.zeros((k, r, sub.shape[2]), np.int32)
            suffixes[:, : r - p] = sub[g.indices, p:]
            bucket = padded_size(k, self.mesh)
            spec_lanes: List[Tuple[int, bytes]] = []
            fill: List[np.ndarray] = []
            prefix_bytes = sub[g.indices[0], :p].tobytes()
            for entry in spec_pool:
                if k + len(fill) >= bucket:
                    break
                if entry[2] or entry[1][:p].tobytes() != prefix_bytes:
                    continue
                entry[2] = True
                spec_lanes.append((k + len(fill), entry[0]))
                srow = np.zeros((r, sub.shape[2]), np.int32)
                srow[: r - p] = entry[1][p:]
                fill.append(srow)
            parts = [suffixes]
            if fill:
                parts.append(np.stack(fill))
            pad = bucket - k - len(fill)
            if pad:
                parts.append(np.repeat(suffixes[:1], pad, axis=0))
            batch = np.concatenate(parts) if len(parts) > 1 else parts[0]
            res = self._fork_kernel(batch, replay_keys(bucket), snap)
            self.pipeline_stats["launches"] += 1
            self.pipeline_stats["lanes_launched"] += bucket
            pending.lanes_launched += bucket
            if self.launch_budget is not None:
                self.launch_budget.note_dispatch("minimize", bucket)
            pending.add_part(
                res.violation,
                np.asarray([idxs[i] for i in g.indices], np.intp),
                np.arange(k, dtype=np.intp),
                spec_lanes,
            )
            self._forker.note_group(k, trunk_steps, hit)
        if scratch:
            self._dispatch_scratch(
                pending, records, [idxs[i] for i in scratch], spec_pool
            )
            self._forker.note_scratch(len(scratch))
        # Leftover speculation (no scratch launch, no prefix-compatible
        # group padding) is simply dropped: speculation only ever rides
        # lanes that already exist — it never pays for its own launch.

    def _pull_codes(self, violation_dev, bucket: int) -> np.ndarray:
        """The ONE blocking verdict pull of the synchronous paths:
        budget-ledgered (dispatch+harvest bracket the inline block) and
        profiler-attributed as a harvest block, so minimizer launches
        show up in the launch ledger the way dpor rounds do."""
        from ..obs.profiler import PROFILER

        if self.launch_budget is not None:
            self.launch_budget.note_dispatch("minimize", bucket)
        t0 = time.perf_counter() if PROFILER.enabled else 0.0
        arr = np.asarray(violation_dev)
        if PROFILER.enabled:
            PROFILER.block("replay", bucket, time.perf_counter() - t0)
        if self.launch_budget is not None:
            self.launch_budget.note_harvest("minimize", bucket)
        return arr

    def _scratch_codes(self, records: np.ndarray, n: int) -> np.ndarray:
        """Replay ``records`` from step 0 and return per-lane violation
        codes. Pads the batch axis to a power-of-two bucket: DDMin levels
        and removal rounds shrink the candidate count every iteration, and
        an unpadded batch would recompile the kernel per distinct size
        (profiled: a 150-delivery raft case spent ~4 min, ~100 compiles,
        in ONE internal stage). Padding rows replay candidate 0 again;
        their verdicts are sliced off."""
        bucket = padded_bucket(n)
        if self.mesh is not None:
            from ..parallel.mesh import pad_batch_to_devices

            bucket = pad_batch_to_devices(bucket, self.mesh)
        if bucket > n:
            records = np.concatenate(
                [records, np.repeat(records[:1], bucket - n, axis=0)]
            )
        res = self.kernel(records, replay_keys(bucket))
        if obs.enabled():
            obs.counter("device.replay.pad_lanes").inc(bucket - n)
        return self._pull_codes(res.violation, bucket)[:n]

    def _forked_codes(self, records: np.ndarray, n: int) -> np.ndarray:
        """Prefix-fork verdicts: group candidates by bucketed shared
        prefix, replay each group's trunk once (LRU-cached across calls —
        consecutive ddmin levels and internal rounds share trunks), fork
        the lanes over the remaining suffixes. Groups too small to
        amortize a trunk fall back to the scratch kernel."""
        from .fork import padded_size

        lengths = (records[:, :, 0] != 0).sum(axis=1)
        groups, scratch = self._forker.plan(records, lengths)
        codes = np.zeros(n, np.int32)
        r = records.shape[1]
        for g in groups:
            if not self._forker.should_fork(g):
                scratch.extend(g.indices)
                continue
            p = g.prefix_len
            trunk_records = np.zeros_like(records[0])
            trunk_records[:p] = records[g.indices[0], :p]
            snap, trunk_steps, hit = self._forker.trunk_hier(
                g.key, trunk_records, jax.random.PRNGKey(0), p
            )
            suffixes = np.zeros(
                (len(g.indices), r, records.shape[2]), np.int32
            )
            suffixes[:, : r - p] = records[g.indices, p:]
            bucket = padded_size(len(g.indices), self.mesh)
            if bucket > len(g.indices):
                suffixes = np.concatenate(
                    [suffixes, np.repeat(suffixes[:1], bucket - len(g.indices), axis=0)]
                )
            res = self._fork_kernel(suffixes, replay_keys(bucket), snap)
            codes[np.asarray(g.indices)] = self._pull_codes(
                res.violation, bucket
            )[: len(g.indices)]
            self._forker.note_group(len(g.indices), trunk_steps, hit)
        if scratch:
            codes[np.asarray(scratch)] = self._scratch_codes(
                records[np.asarray(scratch)], len(scratch)
            )
            self._forker.note_scratch(len(scratch))
        return codes

    def host_executed_trace(
        self,
        candidate: EventTrace,
        externals: Sequence[ExternalEvent],
        violation: Any,
    ) -> Optional[EventTrace]:
        # Keep the tiers' replay power matched: when the device kernel
        # peeks (cfg.replay_peek), the host bookkeeping replay must too,
        # with the SAME prefix budget — a larger host budget would let a
        # candidate host-verify via a longer peek than the device oracle
        # that selected it allows (and vice versa on re-runs).
        sts = STSScheduler(
            self.config, candidate,
            allow_peek=self.cfg.replay_peek > 0,
            max_peek_messages=self.cfg.replay_peek,
        )
        return sts.test_with_trace(candidate, list(externals), violation)


class PendingVerdicts:
    """Handle for a dispatched candidate batch: verdict codes stay on
    device (one ``np.asarray`` per launch happens only inside
    ``harvest``), so the host plans — and speculatively executes — while
    the device crunches. The wall clock between dispatch-return and
    harvest is the pipeline's overlap; the blocking pull inside harvest
    is what's left of the old per-group stall."""

    UNRESOLVED = -(1 << 40)  # outside the int32 violation-code range

    def __init__(self, checker: DeviceReplayChecker, n: int, target_code: int):
        self.checker = checker
        self.n = n
        self.target_code = target_code
        self.codes = np.full(n, self.UNRESOLVED, np.int64)
        self._parts: List[tuple] = []
        self._dispatched_at: Optional[float] = None
        self._verdicts: Optional[List[bool]] = None
        # Lanes launched for this handle (budget ledger: dispatched at
        # launch, harvested when the codes are pulled below).
        self.lanes_launched = 0

    def add_part(self, violation_dev, cand_idx, lane_idx, spec_lanes) -> None:
        self._parts.append((violation_dev, cand_idx, lane_idx, spec_lanes))

    def mark_dispatched(self, dispatch_seconds: float) -> None:
        self.checker.pipeline_stats["dispatch_seconds"] += dispatch_seconds
        self._dispatched_at = time.perf_counter()

    def harvest(self) -> List[bool]:
        """Pull every part's codes host-side (idempotent) and seed the
        checker's speculative verdict cache from the spec lanes."""
        if self._verdicts is not None:
            return self._verdicts
        stats = self.checker.pipeline_stats
        if self._dispatched_at is not None:
            overlap = time.perf_counter() - self._dispatched_at
            stats["overlap_seconds"] += overlap
            obs.counter("pipe.overlap_seconds").inc(overlap)
        t0 = time.perf_counter()
        spec_count = 0
        for violation_dev, cand_idx, lane_idx, spec_lanes in self._parts:
            arr = np.asarray(violation_dev)
            if cand_idx.size:
                self.codes[cand_idx] = arr[lane_idx]
            for lane, digest in spec_lanes:
                self.checker._spec_cache[digest] = int(arr[lane])
                spec_count += 1
        self._parts = []
        wait = time.perf_counter() - t0
        stats["harvest_wait_seconds"] += wait
        stats["spec_dispatched"] += spec_count
        obs.counter("pipe.harvest_wait_seconds").inc(wait)
        if self.lanes_launched:
            from ..obs.profiler import PROFILER

            if PROFILER.enabled:
                PROFILER.block("replay", self.lanes_launched, wait)
            if self.checker.launch_budget is not None:
                self.checker.launch_budget.note_harvest(
                    "minimize", self.lanes_launched
                )
            self.lanes_launched = 0
        if obs.enabled():
            # Host-vs-device split of the pipeline's round-trip time:
            # overlap_seconds is host planning done UNDER device
            # execution, harvest_wait is blocked on the device.
            total = stats["overlap_seconds"] + stats["harvest_wait_seconds"]
            if total > 0:
                obs.gauge("pipe.host_share").set(
                    stats["overlap_seconds"] / total
                )
        if spec_count:
            obs.counter("pipe.spec_dispatched").inc(spec_count)
        if self.n and bool((self.codes == self.UNRESOLVED).any()):
            raise RuntimeError(
                "PendingVerdicts.harvest: unresolved candidate lanes"
            )
        self._verdicts = [int(c) == self.target_code for c in self.codes]
        if obs.enabled():
            obs.counter("device.replay.candidates").inc(self.n)
            obs.counter("device.replay.reproductions").inc(
                sum(self._verdicts)
            )
        return self._verdicts


def make_batched_internal_check(
    checker: DeviceReplayChecker,
    externals: Sequence[ExternalEvent],
    violation: IntViolation,
) -> Callable[[List[EventTrace]], List[Optional[EventTrace]]]:
    """batch_check for BatchedInternalMinimizer: device verdicts for all
    candidates, host execution only for the first reproducing one.

    The returned closure also carries the async-pipeline surface the
    speculative minimizer round uses when the checker runs with
    ``async_min``: ``dispatch_round`` (non-blocking launch with a base
    hint for the gather lowerer + speculative next-round candidates),
    ``host_execute`` (the bookkeeping STS execution, callable BETWEEN
    dispatch and harvest so it overlaps device work), and
    ``supports_async``."""

    def batch_check(candidates: List[EventTrace]) -> List[Optional[EventTrace]]:
        verdicts = checker.verdicts(
            candidates, [externals] * len(candidates), violation.code
        )
        out: List[Optional[EventTrace]] = [None] * len(candidates)
        for i, ok in enumerate(verdicts):
            if ok:
                executed = checker.host_executed_trace(
                    candidates[i], externals, violation
                )
                if executed is not None:
                    out[i] = executed
                    break
        return out

    def dispatch_round(
        candidates: List[EventTrace],
        base: Optional[EventTrace] = None,
        speculate: Optional[List[EventTrace]] = None,
    ) -> PendingVerdicts:
        if base is not None:
            checker.prime_base(base, externals)
        return checker.dispatch(
            candidates,
            [externals] * len(candidates),
            violation.code,
            speculate=[(s, externals) for s in (speculate or [])],
        )

    def host_execute(candidate: EventTrace) -> Optional[EventTrace]:
        return checker.host_executed_trace(candidate, externals, violation)

    batch_check.dispatch_round = dispatch_round
    batch_check.host_execute = host_execute
    batch_check.supports_async = checker.async_enabled
    return batch_check


class DeviceSTSOracle(TestOracle):
    """TestOracle for external-event DDMin backed by the device replay
    kernel: each test() lowers the projected candidate and replays it on
    device; positives are re-executed on the host for the bookkeeping trace.
    ``test_batch`` checks a whole DDMin level at once."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        config: SchedulerConfig,
        original_trace: EventTrace,
        checker: Optional[DeviceReplayChecker] = None,
    ):
        # Pass a shared checker to reuse one compiled replay kernel across
        # pipeline stages.
        self.checker = checker or DeviceReplayChecker(app, cfg, config)
        self.original_trace = original_trace
        self.config = config
        self._primed = False

    @property
    def supports_async(self) -> bool:
        """True when the backing checker runs the async pipeline — what
        the speculative minimizers probe before using dispatch_batch /
        test_window."""
        return self.checker.async_enabled

    def _project(self, externals: Sequence[ExternalEvent]) -> EventTrace:
        return (
            self.original_trace.filter_failure_detector_messages()
            .filter_checkpoint_messages()
            .subsequence_intersection(
                list(externals),
                filter_known_absents=self.config.filter_known_absents,
            )
        )

    def test(self, externals, violation_fingerprint, stats=None, init=None):
        if stats is not None:
            stats.record_replay()
        projected = self._project(externals)
        ok = self.checker.verdicts(
            [projected], [externals], violation_fingerprint.code
        )[0]
        if not ok:
            return None
        return self.checker.host_executed_trace(
            projected, externals, violation_fingerprint
        )

    def test_batch(
        self,
        candidates: Sequence[Sequence[ExternalEvent]],
        violation_fingerprint,
    ) -> List[bool]:
        self._prime()
        projected = [self._project(c) for c in candidates]
        return self.checker.verdicts(
            projected, candidates, violation_fingerprint.code
        )

    def _prime(self) -> None:
        """Register the MASTER base with the gather lowerer: the filtered
        original trace. Every candidate projection — any external subset,
        any known-absent pruning outcome — is an event-subsequence of it
        (projection only ever drops events), so one registration serves
        every ddmin level."""
        if not self.checker.async_enabled or self._primed:
            return
        self._primed = True
        ext = self.original_trace.original_externals
        if ext is None:
            return
        master = (
            self.original_trace.filter_failure_detector_messages()
            .filter_checkpoint_messages()
        )
        self.checker.prime_base(master, list(ext))

    def dispatch_batch(
        self,
        candidates: Sequence[Sequence[ExternalEvent]],
        violation_fingerprint,
        speculate: Optional[Sequence[Sequence[ExternalEvent]]] = None,
    ) -> PendingVerdicts:
        """Non-blocking ``test_batch``: returns the pending handle, with
        ``speculate`` (the predicted NEXT level's candidates) riding the
        launch's idle padded lanes. Requires the checker's async mode."""
        self._prime()
        projected = [self._project(c) for c in candidates]
        spec = [(self._project(s), s) for s in (speculate or [])]
        return self.checker.dispatch(
            projected, candidates, violation_fingerprint.code, speculate=spec
        )

    def test_window(
        self,
        candidates: Sequence[Sequence[ExternalEvent]],
        violation_fingerprint,
    ) -> List[Callable[[], Optional[EventTrace]]]:
        """One device launch for a whole speculation window of ``test``
        calls: returns per-candidate lazy resolvers. ``resolvers[i]()``
        behaves exactly like ``test(candidates[i], ...)`` — device verdict
        gates a host bookkeeping execution — but the device work for the
        whole window was batched up front, so a sequential scan that
        consults only a prefix of the window (stopping at its first
        reproduction) discards the rest as speculation waste."""
        self._prime()
        projected = [self._project(c) for c in candidates]
        verdicts = self.checker.verdicts(
            projected, candidates, violation_fingerprint.code
        )

        def resolver(i: int) -> Optional[EventTrace]:
            if not verdicts[i]:
                return None
            return self.checker.host_executed_trace(
                projected[i], candidates[i], violation_fingerprint
            )

        return [
            (lambda i=i: resolver(i)) for i in range(len(candidates))
        ]
