"""Batched schedule replay: the device-tier STS oracle.

Each lane consumes a prescribed record sequence (the host-lowered expected
trace of one DDMin candidate — see encoding.py): external records are
applied directly; delivery records are matched against the pending pool by
(src, dst, exact message) with FIFO (min arrival seq) disambiguation, and
*skipped when absent* — the STS ignore-absent heuristic
(reference: STSScheduler.scala:405-559) — so a whole minimization level
replays as one vmapped batch.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..dsl import DSLApp
from . import ops
from .core import (
    OP_END,
    REC_NONE,
    REC_DELIVERY,
    REC_EXT_BASE,
    REC_TIMER,
    REC_WILDCARD,
    ST_DONE,
    ST_VIOLATION,
    DeviceConfig,
    RowProposal,
    ScheduleState,
    _append_record,
    check_invariant,
    deliverable_mask,
    delivery_effects,
    external_effects,
    init_state,
    insert_rows,
)
from .explore import _precomputed


class ReplayResult(NamedTuple):
    status: jnp.ndarray
    violation: jnp.ndarray  # int32 final invariant code
    deliveries: jnp.ndarray
    ignored_absent: jnp.ndarray  # int32: expected deliveries with no match


def _is_delivery_kind(kind):
    return (kind == REC_DELIVERY) | (kind == REC_TIMER) | (kind == REC_WILDCARD)


def make_replay_run_lane(app: DSLApp, cfg: DeviceConfig):
    """Unjitted single-lane replay ``run_lane(records, key) -> ReplayResult``
    (composable with vmap/jit/shardings by callers)."""
    init_states, initial_rows = _precomputed(app, cfg)
    big = jnp.int32(2**30)

    def replay_record(state: ScheduleState, rec, active) -> ScheduleState:
        """Fused, branchless record application: the external and delivery
        sides both run with masks (inert op / invalid index for whichever
        doesn't apply) and share ONE pool-insert pass — same shape as the
        fused explore step (both lax.cond branches would execute under vmap
        anyway, and the O(pool) insert machinery dominates)."""
        kind = rec[0]
        # Explicit msg slice: parent-tracked records carry a trailing
        # column that must not leak into message matching.
        a, b, msg = rec[1], rec[2], rec[3 : 3 + cfg.msg_width]
        is_ext = active & (kind >= REC_EXT_BASE)
        is_delivery = active & _is_delivery_kind(kind)
        rec_idx = state.trace_len

        # External side (inert op unless is_ext).
        op = jnp.where(is_ext, kind - REC_EXT_BASE, OP_END)
        state, ext_rows, ext_rec, ext_enabled = external_effects(
            state, cfg, app, initial_rows, init_states, op, a, b, msg
        )

        # Delivery side (invalid index unless is_delivery and matched).
        is_timer_rec = kind == REC_TIMER
        is_wild = kind == REC_WILDCARD
        mask = deliverable_mask(state, cfg)
        exact = (
            (state.pool_dst == b)
            & jnp.all(state.pool_msg == msg[None, :], axis=1)
            & (state.pool_timer == is_timer_rec)
            # Timers self-address; messages match on sender too.
            & (is_timer_rec | (state.pool_src == a))
        )
        # Wildcard (reference: WildCardMatch selectors,
        # STSScheduler.scala:696-708): receiver + class tag only.
        wild = (state.pool_dst == a) & (state.pool_msg[:, 0] == msg[0])
        match = mask & jnp.where(is_wild, wild, exact)
        any_match = jnp.any(match)
        # policy: FIFO (earliest arrival) or, for wildcard "last",
        # latest arrival.
        want_last = is_wild & (b == 1)
        seqs_first = jnp.where(match, state.pool_seq, big)
        seqs_last = jnp.where(match, state.pool_seq, -big)
        idx = jnp.where(
            want_last, jnp.argmax(seqs_last), jnp.argmin(seqs_first)
        ).astype(jnp.int32)
        idx = jnp.where(
            any_match & is_delivery, idx, jnp.int32(cfg.pool_capacity)
        )
        state, del_rows, del_rec = delivery_effects(state, cfg, app, idx)

        rows = RowProposal.concat(ext_rows, del_rows)
        state = insert_rows(
            state, cfg, rows.valid, rows.src, rows.dst, rows.timer,
            rows.parked, rows.msg,
            crec=rec_idx if cfg.record_parents else None,
        )
        if cfg.record_trace:
            delivered = idx < cfg.pool_capacity
            out_rec = jnp.where(delivered, del_rec, ext_rec)
            state = _append_record(
                state, cfg, out_rec, delivered | (is_ext & ext_enabled)
            )
        return state

    def run_lane(records, key) -> ReplayResult:
        state = init_state(app, cfg, key)

        def apply_one(state, ignored, rec):
            before = state.deliveries
            state = replay_record(state, rec, state.status < ST_DONE)
            was_delivery = _is_delivery_kind(rec[0])
            skipped = was_delivery & (state.deliveries == before) & (state.status < ST_DONE)
            return state, ignored + skipped.astype(jnp.int32)

        if cfg.early_exit:
            # Stop at trailing padding (REC_NONE) or a finished lane; under
            # vmap the cond is OR-reduced, so the batch runs only as long
            # as the longest live candidate — minimization candidates
            # shrink far below the shared static record shape.
            n_rec = records.shape[0]

            oh = cfg.use_onehot

            def cond(carry):
                s, _ig, i = carry
                kind = ops.get_scalar(
                    records[:, 0], jnp.minimum(i, n_rec - 1), oh
                )
                return (i < n_rec) & (kind != REC_NONE) & (s.status < ST_DONE)

            def wl_body(carry):
                s, ig, i = carry
                rec = ops.get_row(records, jnp.minimum(i, n_rec - 1), oh)
                s, ig = apply_one(s, ig, rec)
                return (s, ig, i + 1)

            state, ignored, _ = jax.lax.while_loop(
                cond, wl_body, (state, jnp.int32(0), jnp.int32(0))
            )
        else:
            def body(carry, rec):
                state, ignored = carry
                state, ignored = apply_one(state, ignored, rec)
                return (state, ignored), None

            (state, ignored), _ = jax.lax.scan(
                body, (state, jnp.int32(0)), records
            )
        # Aborted lanes (overflow) must not report a verdict computed from
        # truncated state — mask their violation to 0 so batched-oracle
        # consumers reading only `violation` never count them as
        # reproducing.
        aborted = state.status >= ST_DONE
        code = jnp.where(aborted, jnp.int32(0), check_invariant(state, app))
        status = jnp.where(
            aborted,
            state.status,
            jnp.where(code != 0, ST_VIOLATION, ST_DONE),
        ).astype(jnp.int32)
        return ReplayResult(
            status=status,
            violation=code.astype(jnp.int32),
            deliveries=state.deliveries,
            ignored_absent=ignored,
        )

    return run_lane


def make_replay_kernel(app: DSLApp, cfg: DeviceConfig):
    """Returns jitted ``kernel(records[B, R, rec_width], keys[B]) ->
    ReplayResult[B]`` replaying each lane's prescribed schedule."""
    return jax.jit(jax.vmap(make_replay_run_lane(app, cfg)))
