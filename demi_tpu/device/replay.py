"""Batched schedule replay: the device-tier STS oracle.

Each lane consumes a prescribed record sequence (the host-lowered expected
trace of one DDMin candidate — see encoding.py): external records are
applied directly; delivery records are matched against the pending pool by
(src, dst, exact message) with FIFO (min arrival seq) disambiguation, and
*skipped when absent* — the STS ignore-absent heuristic
(reference: STSScheduler.scala:405-559) — so a whole minimization level
replays as one vmapped batch.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..dsl import DSLApp
from . import ops
from .core import (
    OP_END,
    REC_NONE,
    REC_DELIVERY,
    REC_EXT_BASE,
    REC_TIMER,
    REC_WILDCARD,
    ST_DONE,
    ST_VIOLATION,
    DeviceConfig,
    RowProposal,
    ScheduleState,
    _append_record,
    check_invariant,
    deliverable_mask,
    delivery_effects,
    external_effects,
    init_state,
    insert_rows,
)
from .explore import _precomputed


class ReplayResult(NamedTuple):
    status: jnp.ndarray
    violation: jnp.ndarray  # int32 final invariant code
    deliveries: jnp.ndarray
    ignored_absent: jnp.ndarray  # int32: expected deliveries with no match
    # Expected deliveries ENABLED by a successful peek prefix
    # (cfg.replay_peek > 0; 0 otherwise).
    peeked: jnp.ndarray


def _is_delivery_kind(kind):
    return (kind == REC_DELIVERY) | (kind == REC_TIMER) | (kind == REC_WILDCARD)


def _replay_cfg(cfg: DeviceConfig) -> DeviceConfig:
    """Replay matches by content + pool_seq FIFO and never reads the
    incremental head bits — skip their maintenance entirely
    (head_recompute flips track_fifo_heads off; fifo_head_mask is never
    called here). Shared with the prefix-fork trunk runner (device/fork.py)
    so trunk snapshots and fork lanes agree on every array shape."""
    import dataclasses

    if cfg.track_fifo_heads:
        cfg = dataclasses.replace(cfg, head_recompute=True)
    return cfg


def make_replay_record_fn(app: DSLApp, cfg: DeviceConfig):
    """The fused record application ``replay_record(state, rec, active) ->
    (state', peek_hit)`` shared by ``make_replay_run_lane`` and the
    prefix-fork trunk runner. ``cfg`` must be pre-normalized by
    ``_replay_cfg``."""
    init_states, initial_rows = _precomputed(app, cfg)
    big = jnp.int32(2**30)

    def _delivery_match(state: ScheduleState, kind, a, b, msg):
        """Pending-pool match mask for one expected delivery record."""
        is_timer_rec = kind == REC_TIMER
        is_wild = kind == REC_WILDCARD
        mask = deliverable_mask(state, cfg)
        exact = (
            (state.pool_dst == b)
            & jnp.all(state.pool_msg == msg[None, :], axis=1)
            & (state.pool_timer == is_timer_rec)
            # Timers self-address; messages match on sender too.
            & (is_timer_rec | (state.pool_src == a))
        )
        # Wildcard (reference: WildCardMatch selectors,
        # STSScheduler.scala:696-708): receiver + class tag only.
        wild = (state.pool_dst == a) & (state.pool_msg[:, 0] == msg[0])
        return mask & jnp.where(is_wild, wild, exact)

    def _deliver_fifo_pending(state: ScheduleState):
        """Deliver the FIFO-earliest deliverable pending entry (the peek
        probe's unexpected delivery), full effects + insert + trace."""
        dmask = deliverable_mask(state, cfg)
        seqs = jnp.where(dmask, state.pool_seq, big)
        pidx = jnp.where(
            jnp.any(dmask), jnp.argmin(seqs), jnp.int32(cfg.pool_capacity)
        ).astype(jnp.int32)
        rec_idx = state.trace_len
        state, prow, prec = delivery_effects(state, cfg, app, pidx)
        state = insert_rows(
            state, cfg, prow.valid, prow.src, prow.dst, prow.timer,
            prow.parked, prow.msg,
            crec=rec_idx if cfg.record_parents else None,
        )
        if cfg.record_trace:
            state = _append_record(
                state, cfg, prec, pidx < cfg.pool_capacity
            )
        return state

    def replay_record(state: ScheduleState, rec, active):
        """Fused, branchless record application: the external and delivery
        sides both run with masks (inert op / invalid index for whichever
        doesn't apply) and share ONE pool-insert pass — same shape as the
        fused explore step (both lax.cond branches would execute under vmap
        anyway, and the O(pool) insert machinery dominates).

        Returns (state', peek_hit): peek_hit is True when
        ``cfg.replay_peek`` enabled an otherwise-absent expected delivery
        by delivering a pending prefix (device twin of STSScheduler.peek,
        STSScheduler.scala:314-378: keep the enabling prefix, roll the
        whole lane back on failure)."""
        kind = rec[0]
        # Explicit msg slice: parent-tracked records carry a trailing
        # column that must not leak into message matching.
        a, b, msg = rec[1], rec[2], rec[3 : 3 + cfg.msg_width]
        is_ext = active & (kind >= REC_EXT_BASE)
        is_delivery = active & _is_delivery_kind(kind)

        # External side (inert op unless is_ext).
        op = jnp.where(is_ext, kind - REC_EXT_BASE, OP_END)
        state, ext_rows, ext_rec, ext_enabled = external_effects(
            state, cfg, app, initial_rows, init_states, op, a, b, msg
        )

        peek_hit = jnp.bool_(False)
        if cfg.replay_peek:
            # The snapshot is the carry itself (functional rollback): run
            # the probe on a forked state; commit only if the expected
            # delivery became matchable within the budget.
            need = is_delivery & ~jnp.any(
                _delivery_match(state, kind, a, b, msg)
            )

            def peek_cond(carry):
                s, j, found = carry
                return (
                    need
                    & (j < cfg.replay_peek)
                    & ~found
                    & jnp.any(deliverable_mask(s, cfg))
                )

            def peek_body(carry):
                s, j, _ = carry
                s = _deliver_fifo_pending(s)
                found = jnp.any(_delivery_match(s, kind, a, b, msg))
                return s, j + 1, found

            s_peek, _, found = jax.lax.while_loop(
                peek_cond, peek_body, (state, jnp.int32(0), jnp.bool_(False))
            )
            state = jax.tree_util.tree_map(
                lambda old, new: jnp.where(found, new, old), state, s_peek
            )
            peek_hit = found

        # Delivery side (invalid index unless is_delivery and matched).
        # Re-capture the record index: peeked deliveries appended records.
        rec_idx = state.trace_len
        is_wild = kind == REC_WILDCARD
        match = _delivery_match(state, kind, a, b, msg)
        any_match = jnp.any(match)
        # policy: FIFO (earliest arrival) or, for wildcard "last",
        # latest arrival.
        want_last = is_wild & (b == 1)
        seqs_first = jnp.where(match, state.pool_seq, big)
        seqs_last = jnp.where(match, state.pool_seq, -big)
        idx = jnp.where(
            want_last, jnp.argmax(seqs_last), jnp.argmin(seqs_first)
        ).astype(jnp.int32)
        idx = jnp.where(
            any_match & is_delivery, idx, jnp.int32(cfg.pool_capacity)
        )
        state, del_rows, del_rec = delivery_effects(state, cfg, app, idx)

        rows = RowProposal.concat(ext_rows, del_rows)
        state = insert_rows(
            state, cfg, rows.valid, rows.src, rows.dst, rows.timer,
            rows.parked, rows.msg,
            crec=rec_idx if cfg.record_parents else None,
        )
        if cfg.record_trace:
            delivered = idx < cfg.pool_capacity
            out_rec = jnp.where(delivered, del_rec, ext_rec)
            state = _append_record(
                state, cfg, out_rec, delivered | (is_ext & ext_enabled)
            )
        return state, peek_hit

    return replay_record


def make_replay_apply_fn(app: DSLApp, cfg: DeviceConfig):
    """``apply_one(state, ignored, peeked, rec)`` — one record plus the
    ignored-absent / peek accounting, shared by the lane loop below and
    the prefix-fork trunk (device/fork.py). ``cfg`` must be pre-normalized
    by ``_replay_cfg``."""
    replay_record = make_replay_record_fn(app, cfg)

    def apply_one(state, ignored, peeked, rec):
        before = state.deliveries
        state, peek_hit = replay_record(
            state, rec, state.status < ST_DONE
        )
        was_delivery = _is_delivery_kind(rec[0])
        skipped = was_delivery & (state.deliveries == before) & (state.status < ST_DONE)
        return (
            state,
            ignored + skipped.astype(jnp.int32),
            peeked + peek_hit.astype(jnp.int32),
        )

    return apply_one


def make_replay_run_lane(app: DSLApp, cfg: DeviceConfig):
    """Unjitted single-lane replay ``run_lane(records, key,
    start_state=None) -> ReplayResult`` (composable with vmap/jit/shardings
    by callers). ``start_state`` (a device/fork.py PrefixSnapshot) resumes
    the lane from a trunk snapshot — ``records`` are then the remaining
    (left-shifted) suffix; the default None keeps today's lowering
    byte-identical."""
    cfg = _replay_cfg(cfg)
    apply_one = make_replay_apply_fn(app, cfg)

    def run_lane(records, key, start_state=None) -> ReplayResult:
        if start_state is None:
            state = init_state(app, cfg, key)
            ignored0 = peeked0 = jnp.int32(0)
        else:
            # Forked lane: the trunk already applied the shared prefix.
            # rng is per-lane for contract parity with the explore fork
            # (replay itself never consumes it).
            state = start_state.state._replace(rng=key)
            ignored0 = start_state.ignored
            peeked0 = start_state.peeked

        if cfg.early_exit:
            # Stop at trailing padding (REC_NONE) or a finished lane; under
            # vmap the cond is OR-reduced, so the batch runs only as long
            # as the longest live candidate — minimization candidates
            # shrink far below the shared static record shape.
            n_rec = records.shape[0]

            oh = cfg.use_onehot

            def cond(carry):
                s, _ig, _pk, i = carry
                kind = ops.get_scalar(
                    records[:, 0], jnp.minimum(i, n_rec - 1), oh
                )
                return (i < n_rec) & (kind != REC_NONE) & (s.status < ST_DONE)

            def wl_body(carry):
                s, ig, pk, i = carry
                rec = ops.get_row(records, jnp.minimum(i, n_rec - 1), oh)
                s, ig, pk = apply_one(s, ig, pk, rec)
                return (s, ig, pk, i + 1)

            state, ignored, peeked, _ = jax.lax.while_loop(
                cond, wl_body,
                (state, ignored0, peeked0, jnp.int32(0)),
            )
        else:
            def body(carry, rec):
                state, ignored, peeked = carry
                state, ignored, peeked = apply_one(state, ignored, peeked, rec)
                return (state, ignored, peeked), None

            (state, ignored, peeked), _ = jax.lax.scan(
                body, (state, ignored0, peeked0), records
            )
        # Aborted lanes (overflow) must not report a verdict computed from
        # truncated state — mask their violation to 0 so batched-oracle
        # consumers reading only `violation` never count them as
        # reproducing.
        aborted = state.status >= ST_DONE
        code = jnp.where(aborted, jnp.int32(0), check_invariant(state, app))
        status = jnp.where(
            aborted,
            state.status,
            jnp.where(code != 0, ST_VIOLATION, ST_DONE),
        ).astype(jnp.int32)
        return ReplayResult(
            status=status,
            violation=code.astype(jnp.int32),
            deliveries=state.deliveries,
            ignored_absent=ignored,
            peeked=peeked,
        )

    return run_lane


def make_replay_kernel(app: DSLApp, cfg: DeviceConfig, start_state: bool = False):
    """Returns jitted ``kernel(records[B, R, rec_width], keys[B]) ->
    ReplayResult[B]`` replaying each lane's prescribed schedule.

    With ``start_state=True`` the kernel takes a third argument — a
    device/fork.py ``PrefixSnapshot`` shared across the lane axis
    (``vmap in_axes=None``) — and ``records`` are each lane's remaining
    suffix; False keeps the two-argument lowering byte-identical."""
    run_lane = make_replay_run_lane(app, cfg)
    if not start_state:
        return jax.jit(jax.vmap(run_lane))
    return jax.jit(
        jax.vmap(
            lambda records, key, snap: run_lane(records, key, snap),
            in_axes=(0, 0, None),
        )
    )
