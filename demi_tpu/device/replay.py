"""Batched schedule replay: the device-tier STS oracle.

Each lane consumes a prescribed record sequence (the host-lowered expected
trace of one DDMin candidate — see encoding.py): external records are
applied directly; delivery records are matched against the pending pool by
(src, dst, exact message) with FIFO (min arrival seq) disambiguation, and
*skipped when absent* — the STS ignore-absent heuristic
(reference: STSScheduler.scala:405-559) — so a whole minimization level
replays as one vmapped batch.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..dsl import DSLApp
from .core import (
    REC_DELIVERY,
    REC_EXT_BASE,
    REC_TIMER,
    REC_WILDCARD,
    ST_DONE,
    ST_VIOLATION,
    DeviceConfig,
    ScheduleState,
    apply_external_op,
    check_invariant,
    deliver_index,
    deliverable_mask,
    init_state,
)
from .explore import _precomputed


class ReplayResult(NamedTuple):
    status: jnp.ndarray
    violation: jnp.ndarray  # int32 final invariant code
    deliveries: jnp.ndarray
    ignored_absent: jnp.ndarray  # int32: expected deliveries with no match


def _is_delivery_kind(kind):
    return (kind == REC_DELIVERY) | (kind == REC_TIMER) | (kind == REC_WILDCARD)


def make_replay_run_lane(app: DSLApp, cfg: DeviceConfig):
    """Unjitted single-lane replay ``run_lane(records, key) -> ReplayResult``
    (composable with vmap/jit/shardings by callers)."""
    init_states, initial_rows = _precomputed(app, cfg)
    big = jnp.int32(2**30)

    def replay_record(state: ScheduleState, rec) -> ScheduleState:
        kind = rec[0]
        # Explicit msg slice: parent-tracked records carry a trailing
        # column that must not leak into message matching.
        a, b, msg = rec[1], rec[2], rec[3 : 3 + cfg.msg_width]

        def apply_ext(state):
            return apply_external_op(
                state, cfg, app, initial_rows, init_states,
                kind - REC_EXT_BASE, a, b, msg,
            )

        def apply_delivery(state):
            is_timer_rec = kind == REC_TIMER
            is_wild = kind == REC_WILDCARD
            mask = deliverable_mask(state, cfg)
            exact = (
                (state.pool_dst == b)
                & jnp.all(state.pool_msg == msg[None, :], axis=1)
                & (state.pool_timer == is_timer_rec)
                # Timers self-address; messages match on sender too.
                & (is_timer_rec | (state.pool_src == a))
            )
            # Wildcard (reference: WildCardMatch selectors,
            # STSScheduler.scala:696-708): receiver + class tag only.
            wild = (state.pool_dst == a) & (state.pool_msg[:, 0] == msg[0])
            match = mask & jnp.where(is_wild, wild, exact)
            any_match = jnp.any(match)
            # policy: FIFO (earliest arrival) or, for wildcard "last",
            # latest arrival.
            want_last = is_wild & (b == 1)
            seqs_first = jnp.where(match, state.pool_seq, big)
            seqs_last = jnp.where(match, state.pool_seq, -big)
            idx = jnp.where(
                want_last, jnp.argmax(seqs_last), jnp.argmin(seqs_first)
            ).astype(jnp.int32)
            idx = jnp.where(any_match, idx, jnp.int32(cfg.pool_capacity))
            return deliver_index(state, cfg, app, idx)

        is_ext = kind >= REC_EXT_BASE
        is_delivery = _is_delivery_kind(kind)
        state = jax.lax.cond(
            is_ext,
            apply_ext,
            lambda s: jax.lax.cond(is_delivery, apply_delivery, lambda x: x, s),
            state,
        )
        return state

    def run_lane(records, key) -> ReplayResult:
        state = init_state(app, cfg, key)

        def body(carry, rec):
            state, ignored = carry
            before = state.deliveries
            state = jax.lax.cond(
                state.status >= ST_DONE, lambda s: s, lambda s: replay_record(s, rec), state
            )
            was_delivery = _is_delivery_kind(rec[0])
            skipped = was_delivery & (state.deliveries == before) & (state.status < ST_DONE)
            return (state, ignored + skipped.astype(jnp.int32)), None

        (state, ignored), _ = jax.lax.scan(body, (state, jnp.int32(0)), records)
        # Aborted lanes (overflow) must not report a verdict computed from
        # truncated state — mask their violation to 0 so batched-oracle
        # consumers reading only `violation` never count them as
        # reproducing.
        aborted = state.status >= ST_DONE
        code = jnp.where(aborted, jnp.int32(0), check_invariant(state, app))
        status = jnp.where(
            aborted,
            state.status,
            jnp.where(code != 0, ST_VIOLATION, ST_DONE),
        ).astype(jnp.int32)
        return ReplayResult(
            status=status,
            violation=code.astype(jnp.int32),
            deliveries=state.deliveries,
            ignored_absent=ignored,
        )

    return run_lane


def make_replay_kernel(app: DSLApp, cfg: DeviceConfig):
    """Returns jitted ``kernel(records[B, R, rec_width], keys[B]) ->
    ReplayResult[B]`` replaying each lane's prescribed schedule."""
    return jax.jit(jax.vmap(make_replay_run_lane(app, cfg)))
