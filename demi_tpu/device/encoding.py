"""Host↔device lowering: external-event programs, expected traces, and
device-trace reconstruction.

The host tier owns trace surgery (subsequence intersection, wildcarding);
this module lowers its outputs to the int32 record/op encodings the kernels
consume, and lifts device explore traces back into host EventTraces (by
guided re-execution on the host oracle, so the lifted trace carries proper
Unique ids, MsgSends, and markers for the minimization stack).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..dsl import DSLApp
from ..events import (
    BeginWaitCondition,
    BeginWaitQuiescence,
    HardKillEvent,
    KillEvent,
    MsgEvent,
    MsgSend,
    PartitionEvent,
    Quiescence,
    SpawnEvent,
    TimerDelivery,
    UnPartitionEvent,
)
from ..external_events import (
    ExternalEvent,
    HardKill,
    Kill,
    Partition,
    Send,
    Start,
    UnPartition,
    WaitCondition,
    WaitQuiescence,
)
from ..events import WildCardMatch
from ..trace import EventTrace
from .core import (
    OP_END,
    OP_HARDKILL,
    OP_KILL,
    OP_PARTITION,
    OP_SEND,
    OP_START,
    OP_UNPARTITION,
    OP_WAIT,
    OP_WAITCOND,
    REC_DELIVERY,
    REC_EXT_BASE,
    REC_NONE,
    REC_TIMER,
    REC_WILDCARD,
    DeviceConfig,
)
from .explore import ExtProgram


def _msg_row(app: DSLApp, msg, width: int) -> List[int]:
    row = list(int(x) for x in msg)
    assert len(row) <= width, f"message {msg!r} wider than msg_width={width}"
    return row + [0] * (width - len(row))


def lower_program(
    app: DSLApp, cfg: DeviceConfig, externals: Sequence[ExternalEvent]
) -> ExtProgram:
    """Lower an external-event program to op arrays. WaitCondition lowers
    via its ``cond_id`` (DSLApp.conditions); host-closure WaitCondition
    and CodeBlock are host-tier-only and rejected here."""
    e, w = cfg.max_external_ops, cfg.msg_width
    ops = np.zeros(e, np.int32)
    a = np.zeros(e, np.int32)
    b = np.zeros(e, np.int32)
    msg = np.zeros((e, w), np.int32)
    if len(externals) > e:
        raise ValueError(f"program length {len(externals)} > max_external_ops {e}")
    for i, ev in enumerate(externals):
        if isinstance(ev, Start):
            ops[i], a[i] = OP_START, app.actor_id(ev.name)
        elif isinstance(ev, Kill):
            ops[i], a[i] = OP_KILL, app.actor_id(ev.name)
        elif isinstance(ev, HardKill):
            ops[i], a[i] = OP_HARDKILL, app.actor_id(ev.name)
        elif isinstance(ev, Send):
            ops[i], a[i] = OP_SEND, app.actor_id(ev.name)
            msg[i] = _msg_row(app, ev.message(), w)
        elif isinstance(ev, WaitQuiescence):
            ops[i] = OP_WAIT
            a[i] = ev.budget or 0  # field a carries the bounded-wait budget
        elif isinstance(ev, WaitCondition):
            if ev.cond_id is None:
                raise TypeError(
                    "WaitCondition with a host closure is host-tier-only; "
                    "give the app a DSLApp.conditions table and pass "
                    "cond_id to lower it to the device tier"
                )
            if not (0 <= ev.cond_id < len(app.conditions)):
                raise ValueError(
                    f"cond_id {ev.cond_id} out of range for "
                    f"{len(app.conditions)} app conditions"
                )
            ops[i] = OP_WAITCOND
            a[i] = ev.cond_id
            b[i] = ev.budget or 0
        elif isinstance(ev, Partition):
            ops[i], a[i], b[i] = OP_PARTITION, app.actor_id(ev.a), app.actor_id(ev.b)
        elif isinstance(ev, UnPartition):
            ops[i], a[i], b[i] = OP_UNPARTITION, app.actor_id(ev.a), app.actor_id(ev.b)
        else:
            raise TypeError(f"{type(ev).__name__} is not lowerable to the device tier")
    _check_msg_range(cfg, msg)
    return ExtProgram(op=ops, a=a, b=b, msg=msg)


def _check_msg_range(cfg: DeviceConfig, msg: np.ndarray) -> None:
    """Narrow storage (msg_dtype='int16') silently wraps out-of-range
    payloads on device; reject them at the host lowering boundary."""
    if cfg.msg_dtype == "int16" and msg.size:
        lo, hi = np.iinfo(np.int16).min, np.iinfo(np.int16).max
        if msg.min() < lo or msg.max() > hi:
            raise ValueError(
                "message payload exceeds int16 range; use msg_dtype='int32' "
                f"(got values in [{msg.min()}, {msg.max()}])"
            )


def stack_programs(programs: Sequence[ExtProgram]) -> ExtProgram:
    return ExtProgram(
        op=np.stack([p.op for p in programs]),
        a=np.stack([p.a for p in programs]),
        b=np.stack([p.b for p in programs]),
        msg=np.stack([p.msg for p in programs]),
    )


def _actor_or_external(app: DSLApp, name: str) -> int:
    try:
        return app.actor_id(name)
    except (KeyError, ValueError):
        return app.num_actors


def lower_expected_matrix(
    app: DSLApp,
    cfg: DeviceConfig,
    trace: EventTrace,
    externals: Sequence[ExternalEvent],
) -> Tuple[List[int], np.ndarray, np.ndarray]:
    """Matrix form of the expected-trace lowering: ``(uids, rows, mask)``
    where ``mask[k]`` marks trace event k as having a device row and
    ``rows`` is the [mask.sum(), 3 + msg_width] int32 matrix of those
    rows in trace order. The per-event dispatch writes straight into the
    preallocated matrix — no per-row Python list building — and every
    downstream consumer (``lower_expected_trace``, the
    ``CandidateLowerer`` full path, ``steering_prescription``) packs or
    filters it with array ops. A ``mask[k]=False`` event has no device
    meaning in replay (internal sends, wait/quiescence markers).

    Each row is a pure function of the event itself (plus its own
    external Send's re-bound payload), which is what makes the
    ``CandidateLowerer``'s row-gather sound: a candidate that is an
    event-subsequence of a base trace lowers to exactly the base's rows
    for the surviving uids."""
    w = cfg.msg_width
    rebound = trace.recompute_external_msg_sends(externals)
    n_events = len(trace.events)
    rows = np.zeros((n_events, 3 + w), np.int32)
    mask = np.zeros(n_events, bool)
    uids: List[int] = []
    uid_payload = {}
    k = 0
    for u, ev in zip(trace.events, rebound):
        uids.append(u.id)
        out = rows[k]
        if isinstance(ev, SpawnEvent):
            out[0], out[1] = REC_EXT_BASE + OP_START, app.actor_id(ev.name)
        elif isinstance(ev, KillEvent):
            out[0], out[1] = REC_EXT_BASE + OP_KILL, app.actor_id(ev.name)
        elif isinstance(ev, HardKillEvent):
            out[0], out[1] = REC_EXT_BASE + OP_HARDKILL, app.actor_id(ev.name)
        elif isinstance(ev, PartitionEvent):
            out[0] = REC_EXT_BASE + OP_PARTITION
            out[1], out[2] = app.actor_id(ev.a), app.actor_id(ev.b)
        elif isinstance(ev, UnPartitionEvent):
            out[0] = REC_EXT_BASE + OP_UNPARTITION
            out[1], out[2] = app.actor_id(ev.a), app.actor_id(ev.b)
        elif isinstance(ev, MsgSend):
            if ev.is_external:
                payload = _msg_row(app, ev.msg, w)
                uid_payload[u.id] = payload
                out[0], out[1] = REC_EXT_BASE + OP_SEND, app.actor_id(ev.rcv)
                out[3:] = payload
            else:
                continue  # internal sends re-occur as delivery side effects
        elif isinstance(ev, MsgEvent):
            if isinstance(ev.msg, WildCardMatch):
                wc = ev.msg
                if not isinstance(wc.class_tag, int):
                    raise TypeError(
                        "device wildcard replay needs int class tags "
                        f"(got {wc.class_tag!r})"
                    )
                if wc.selector is not None or wc.policy not in ("first", "last"):
                    raise TypeError(
                        f"wildcard policy {wc.policy!r}/selector is not "
                        "lowerable to the device tier"
                    )
                out[0], out[1] = REC_WILDCARD, app.actor_id(ev.rcv)
                out[2] = 1 if wc.policy == "last" else 0
                out[3] = wc.class_tag
            else:
                payload = uid_payload.get(u.id, None)
                if payload is None:
                    payload = _msg_row(app, ev.msg, w)
                out[0] = REC_DELIVERY
                out[1] = _actor_or_external(app, ev.snd)
                out[2] = app.actor_id(ev.rcv)
                out[3:] = payload
        elif isinstance(ev, TimerDelivery):
            rid = app.actor_id(ev.rcv)
            out[0], out[1], out[2] = REC_TIMER, rid, rid
            out[3:] = _msg_row(app, ev.msg, w)
        else:
            continue  # Quiescence / wait markers: no device meaning
        mask[len(uids) - 1] = True
        k += 1
    return uids, rows[:k], mask




def _pack_matrix(
    cfg: DeviceConfig, rows: np.ndarray, max_records: int
) -> np.ndarray:
    """Pad a compact [n, <=rec_width] int32 row matrix into the
    [max_records, rec_width] array the replay kernels consume, with the
    shared guards — the vectorized core of ``_pack_records``."""
    n = len(rows)
    if n > max_records:
        raise ValueError(f"expected trace has {n} records > {max_records}")
    # Records are compact (no mid-sequence REC_NONE holes): the replay
    # kernel's early-exit path terminates at the first zero-kind record,
    # which must therefore only ever be trailing padding. (ValueError, not
    # assert: this guard must survive python -O.)
    if n and (np.asarray(rows)[:, 0] == 0).any():
        raise ValueError("REC_NONE hole in expected trace records")
    # Rows are kind/a/b/msg; right-pad to the cfg's record width (a
    # record_parents cfg has a trailing parent column, zero here).
    out = np.zeros((max_records, cfg.rec_width), np.int32)
    if n:
        out[:n, : rows.shape[1]] = rows
    _check_msg_range(cfg, out[:, 3 : 3 + cfg.msg_width])
    return out


def _pack_records(
    cfg: DeviceConfig, recs: Sequence[Sequence[int]], max_records: int
) -> np.ndarray:
    """Assemble compact record rows into the padded [max_records,
    rec_width] array the replay kernels consume, with the shared guards.
    Uniform-width rows (the lowering always emits 3 + msg_width) stack in
    one array conversion; ragged inputs fall back to a per-row copy."""
    if len(recs) > max_records:
        raise ValueError(f"expected trace has {len(recs)} records > {max_records}")
    if not len(recs):
        return _pack_matrix(cfg, np.zeros((0, 3), np.int32), max_records)
    try:
        rows = np.asarray(recs, np.int32)
        assert rows.ndim == 2
    except (ValueError, AssertionError):
        if any(r[0] == 0 for r in recs):
            raise ValueError("REC_NONE hole in expected trace records")
        out = np.zeros((max_records, cfg.rec_width), np.int32)
        for i, r in enumerate(recs):
            out[i, : len(r)] = r
        _check_msg_range(cfg, out[:, 3 : 3 + cfg.msg_width])
        return out
    return _pack_matrix(cfg, rows, max_records)


def lower_expected_trace(
    app: DSLApp,
    cfg: DeviceConfig,
    trace: EventTrace,
    externals: Sequence[ExternalEvent],
    max_records: int,
) -> np.ndarray:
    """Lower a projected/filtered EventTrace (the output of
    subsequence_intersection) into replay records [max_records, rec_width].

    External Send payloads are re-bound via their constructors first, and
    the corresponding delivery records carry the re-bound payload (uid
    linkage), so payload shrinking composes with device replay."""
    _uids, rows, _mask = lower_expected_matrix(app, cfg, trace, externals)
    return _pack_matrix(cfg, rows, max_records)


class CandidateLowerer:
    """Lower-once/gather-many candidate lowering (the async-minimization
    pipeline's host-side hot-path fix): ddmin and internal-minimization
    candidates are event-subsequences of one base trace, so the base is
    lowered to per-event rows ONCE and each candidate materializes as a
    NumPy row-gather instead of a fresh ``lower_expected_trace`` Python
    loop. Soundness rests on ``lower_expected_matrix``: a surviving event's
    row depends only on the event (and its own Send's payload), and
    subsequence projection / delivery removal reuse the base trace's
    ``Unique`` objects, so gathered rows equal a from-scratch lowering
    byte-for-byte (pinned by tests/test_async_min.py).

    Two LRU layers: ``bases`` (uid -> row-index maps + the packed row
    matrix) and ``candidates`` keyed by (base token, surviving-uid tuple)
    — equivalently the (trace id, removed-index set) of the level that
    produced the candidate. Unknown uids (wildcarded deliveries get fresh
    Uniques, host re-executions renumber) fall back to a full lowering,
    which is then registered as a new base so the NEXT round's candidates
    gather again."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        max_records: int,
        base_capacity: int = 8,
        candidate_capacity: int = 256,
    ):
        self.app = app
        self.cfg = cfg
        self.max_records = max_records
        self.base_capacity = base_capacity
        self.candidate_capacity = candidate_capacity
        self._bases: "OrderedDict[int, dict]" = OrderedDict()
        self._candidates: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._base_token = 0
        self.stats = {"full": 0, "gathers": 0, "cached": 0, "bases": 0}

    def hit_rate(self) -> float:
        """Fraction of lowerings served without a full Python lowering."""
        served = self.stats["gathers"] + self.stats["cached"]
        total = served + self.stats["full"]
        return served / total if total else 0.0

    def _register_base(self, rows: np.ndarray, row_of, ref) -> int:
        self._base_token += 1
        self._bases[self._base_token] = {
            "rows": rows, "row_of": row_of, "ref": ref,
        }
        self.stats["bases"] += 1
        while len(self._bases) > self.base_capacity:
            self._bases.popitem(last=False)
        return self._base_token

    def register_base(
        self, trace: EventTrace, externals: Sequence[ExternalEvent]
    ) -> None:
        """Explicitly lower+register a base (e.g. a round's baseline or a
        ddmin level's current dag projection) so the level's candidates
        gather instead of full-lowering. Idempotent enough: a base whose
        uid set is already gatherable registers via the gather path."""
        self._lower_impl(trace, externals, register=True)

    def lower(
        self, trace: EventTrace, externals: Sequence[ExternalEvent]
    ) -> Tuple[np.ndarray, bytes]:
        """Lower one candidate; returns (records [max_records, rec_width],
        digest). The digest keys the speculative verdict cache: verdicts
        are a pure function of the record bytes (replay lanes never
        consume rng), so equal digests may share a verdict bit-exactly."""
        return self._lower_impl(trace, externals, register=False)

    def _lower_impl(self, trace, externals, register: bool):
        # Keys are Unique WRAPPER identities, not Unique.id: a MsgSend and
        # its delivery share one uid (the send/delivery linkage), and
        # wildcard minimization rewraps deliveries into fresh events under
        # the same uid — both would alias a uid-keyed map. The base holds
        # references to its wrappers, so a live id() can't be reused and
        # ``ref.get(id(u)) is u`` means exactly "this event, unmodified,
        # is part of the base". Identity misses fall back to a full
        # lowering (correct for wildcarded / re-executed traces).
        keys = tuple(id(u) for u in trace.events)
        for token in reversed(self._bases):
            base = self._bases[token]
            row_of, ref = base["row_of"], base["ref"]
            idx: List[int] = []
            ok = True
            for u in trace.events:
                k = id(u)
                if ref.get(k) is not u:
                    ok = False
                    break
                r = row_of.get(k)
                if r is not None:
                    idx.append(r)
            if ok and len(idx) > 1:
                # Subsequence order check, one vectorized pass: gathered
                # row indices must be strictly increasing.
                arr = np.asarray(idx, np.intp)
                ok = bool((arr[1:] > arr[:-1]).all())
            if not ok:
                continue
            cand_key = (token, keys)
            # register=True must reach the gather path below (the point
            # is to install a new base), so it skips the shortcut.
            hit = None if register else self._candidates.get(cand_key)
            if hit is not None:
                self._candidates.move_to_end(cand_key)
                self.stats["cached"] += 1
                obs.counter("pipe.lower_cached").inc()
                return hit
            if len(idx) > self.max_records:
                raise ValueError(
                    f"expected trace has {len(idx)} records > {self.max_records}"
                )
            rows = base["rows"][np.asarray(idx, np.intp)] if idx else (
                np.zeros((0, self.cfg.rec_width), np.int32)
            )
            out = np.zeros((self.max_records, self.cfg.rec_width), np.int32)
            out[: len(idx)] = rows
            digest = hashlib.blake2b(out.tobytes(), digest_size=16).digest()
            self.stats["gathers"] += 1
            obs.counter("pipe.lower_gather").inc()
            if register:
                new_row_of = {}
                for u in trace.events:
                    if id(u) in row_of:
                        new_row_of[id(u)] = len(new_row_of)
                self._register_base(
                    rows, new_row_of, {id(u): u for u in trace.events}
                )
            self._remember_candidate((token, keys), out, digest)
            return out, digest

        # No base covers this candidate: full lowering, registered as a
        # fresh base so the next round's subsequences gather.
        _uids, rows, has_row = lower_expected_matrix(
            self.app, self.cfg, trace, externals
        )
        out = _pack_matrix(self.cfg, rows, self.max_records)
        digest = hashlib.blake2b(out.tobytes(), digest_size=16).digest()
        self.stats["full"] += 1
        obs.counter("pipe.lower_full").inc()
        row_of: dict = {}
        for u, has in zip(trace.events, has_row):
            if has:
                row_of[id(u)] = len(row_of)
        token = self._register_base(
            out[: len(rows)].copy(), row_of, {id(u): u for u in trace.events}
        )
        self._remember_candidate((token, keys), out, digest)
        return out, digest

    def _remember_candidate(self, key, records, digest) -> None:
        self._candidates[key] = (records, digest)
        self._candidates.move_to_end(key)
        while len(self._candidates) > self.candidate_capacity:
            self._candidates.popitem(last=False)


# ---------------------------------------------------------------------------
# Lifting device explore traces back to host EventTraces
# ---------------------------------------------------------------------------

def device_trace_to_guide(
    app: DSLApp, records: np.ndarray, trace_len: int
) -> List[Tuple]:
    """Decode a device-recorded trace into a host guide: a list of
    ("ext", op, a, b, msg) / ("deliver", src, dst, msg, is_timer) steps.
    Accepts parent-tracked records (extra trailing column) transparently."""
    guide: List[Tuple] = []
    for i in range(int(trace_len)):
        rec = records[i]
        kind = int(rec[0])
        msg = tuple(int(x) for x in rec[3 : 3 + app.msg_width])
        if kind == REC_NONE:
            continue
        if kind in (REC_DELIVERY, REC_TIMER):
            guide.append(("deliver", int(rec[1]), int(rec[2]), msg, kind == REC_TIMER))
        elif kind >= REC_EXT_BASE:
            guide.append(("ext", kind - REC_EXT_BASE, int(rec[1]), int(rec[2]), msg))
    return guide
