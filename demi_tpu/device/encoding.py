"""Host↔device lowering: external-event programs, expected traces, and
device-trace reconstruction.

The host tier owns trace surgery (subsequence intersection, wildcarding);
this module lowers its outputs to the int32 record/op encodings the kernels
consume, and lifts device explore traces back into host EventTraces (by
guided re-execution on the host oracle, so the lifted trace carries proper
Unique ids, MsgSends, and markers for the minimization stack).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..dsl import DSLApp
from ..events import (
    BeginWaitCondition,
    BeginWaitQuiescence,
    HardKillEvent,
    KillEvent,
    MsgEvent,
    MsgSend,
    PartitionEvent,
    Quiescence,
    SpawnEvent,
    TimerDelivery,
    UnPartitionEvent,
)
from ..external_events import (
    ExternalEvent,
    HardKill,
    Kill,
    Partition,
    Send,
    Start,
    UnPartition,
    WaitCondition,
    WaitQuiescence,
)
from ..events import WildCardMatch
from ..trace import EventTrace
from .core import (
    OP_END,
    OP_HARDKILL,
    OP_KILL,
    OP_PARTITION,
    OP_SEND,
    OP_START,
    OP_UNPARTITION,
    OP_WAIT,
    OP_WAITCOND,
    REC_DELIVERY,
    REC_EXT_BASE,
    REC_NONE,
    REC_TIMER,
    REC_WILDCARD,
    DeviceConfig,
)
from .explore import ExtProgram


def _msg_row(app: DSLApp, msg, width: int) -> List[int]:
    row = list(int(x) for x in msg)
    assert len(row) <= width, f"message {msg!r} wider than msg_width={width}"
    return row + [0] * (width - len(row))


def lower_program(
    app: DSLApp, cfg: DeviceConfig, externals: Sequence[ExternalEvent]
) -> ExtProgram:
    """Lower an external-event program to op arrays. WaitCondition lowers
    via its ``cond_id`` (DSLApp.conditions); host-closure WaitCondition
    and CodeBlock are host-tier-only and rejected here."""
    e, w = cfg.max_external_ops, cfg.msg_width
    ops = np.zeros(e, np.int32)
    a = np.zeros(e, np.int32)
    b = np.zeros(e, np.int32)
    msg = np.zeros((e, w), np.int32)
    if len(externals) > e:
        raise ValueError(f"program length {len(externals)} > max_external_ops {e}")
    for i, ev in enumerate(externals):
        if isinstance(ev, Start):
            ops[i], a[i] = OP_START, app.actor_id(ev.name)
        elif isinstance(ev, Kill):
            ops[i], a[i] = OP_KILL, app.actor_id(ev.name)
        elif isinstance(ev, HardKill):
            ops[i], a[i] = OP_HARDKILL, app.actor_id(ev.name)
        elif isinstance(ev, Send):
            ops[i], a[i] = OP_SEND, app.actor_id(ev.name)
            msg[i] = _msg_row(app, ev.message(), w)
        elif isinstance(ev, WaitQuiescence):
            ops[i] = OP_WAIT
            a[i] = ev.budget or 0  # field a carries the bounded-wait budget
        elif isinstance(ev, WaitCondition):
            if ev.cond_id is None:
                raise TypeError(
                    "WaitCondition with a host closure is host-tier-only; "
                    "give the app a DSLApp.conditions table and pass "
                    "cond_id to lower it to the device tier"
                )
            if not (0 <= ev.cond_id < len(app.conditions)):
                raise ValueError(
                    f"cond_id {ev.cond_id} out of range for "
                    f"{len(app.conditions)} app conditions"
                )
            ops[i] = OP_WAITCOND
            a[i] = ev.cond_id
            b[i] = ev.budget or 0
        elif isinstance(ev, Partition):
            ops[i], a[i], b[i] = OP_PARTITION, app.actor_id(ev.a), app.actor_id(ev.b)
        elif isinstance(ev, UnPartition):
            ops[i], a[i], b[i] = OP_UNPARTITION, app.actor_id(ev.a), app.actor_id(ev.b)
        else:
            raise TypeError(f"{type(ev).__name__} is not lowerable to the device tier")
    _check_msg_range(cfg, msg)
    return ExtProgram(op=ops, a=a, b=b, msg=msg)


def _check_msg_range(cfg: DeviceConfig, msg: np.ndarray) -> None:
    """Narrow storage (msg_dtype='int16') silently wraps out-of-range
    payloads on device; reject them at the host lowering boundary."""
    if cfg.msg_dtype == "int16" and msg.size:
        lo, hi = np.iinfo(np.int16).min, np.iinfo(np.int16).max
        if msg.min() < lo or msg.max() > hi:
            raise ValueError(
                "message payload exceeds int16 range; use msg_dtype='int32' "
                f"(got values in [{msg.min()}, {msg.max()}])"
            )


def stack_programs(programs: Sequence[ExtProgram]) -> ExtProgram:
    return ExtProgram(
        op=np.stack([p.op for p in programs]),
        a=np.stack([p.a for p in programs]),
        b=np.stack([p.b for p in programs]),
        msg=np.stack([p.msg for p in programs]),
    )


def _actor_or_external(app: DSLApp, name: str) -> int:
    try:
        return app.actor_id(name)
    except (KeyError, ValueError):
        return app.num_actors


def lower_expected_trace(
    app: DSLApp,
    cfg: DeviceConfig,
    trace: EventTrace,
    externals: Sequence[ExternalEvent],
    max_records: int,
) -> np.ndarray:
    """Lower a projected/filtered EventTrace (the output of
    subsequence_intersection) into replay records [max_records, rec_width].

    External Send payloads are re-bound via their constructors first, and
    the corresponding delivery records carry the re-bound payload (uid
    linkage), so payload shrinking composes with device replay."""
    w = cfg.msg_width
    rebound = trace.recompute_external_msg_sends(externals)
    recs: List[List[int]] = []
    uid_payload = {}
    for u, ev in zip(trace.events, rebound):
        if isinstance(ev, SpawnEvent):
            recs.append([REC_EXT_BASE + OP_START, app.actor_id(ev.name), 0] + [0] * w)
        elif isinstance(ev, KillEvent):
            recs.append([REC_EXT_BASE + OP_KILL, app.actor_id(ev.name), 0] + [0] * w)
        elif isinstance(ev, HardKillEvent):
            recs.append([REC_EXT_BASE + OP_HARDKILL, app.actor_id(ev.name), 0] + [0] * w)
        elif isinstance(ev, PartitionEvent):
            recs.append(
                [REC_EXT_BASE + OP_PARTITION, app.actor_id(ev.a), app.actor_id(ev.b)]
                + [0] * w
            )
        elif isinstance(ev, UnPartitionEvent):
            recs.append(
                [REC_EXT_BASE + OP_UNPARTITION, app.actor_id(ev.a), app.actor_id(ev.b)]
                + [0] * w
            )
        elif isinstance(ev, MsgSend):
            if ev.is_external:
                payload = _msg_row(app, ev.msg, w)
                uid_payload[u.id] = payload
                recs.append(
                    [REC_EXT_BASE + OP_SEND, app.actor_id(ev.rcv), 0] + payload
                )
            # internal sends re-occur as delivery side effects
        elif isinstance(ev, MsgEvent):
            if isinstance(ev.msg, WildCardMatch):
                wc = ev.msg
                if not isinstance(wc.class_tag, int):
                    raise TypeError(
                        "device wildcard replay needs int class tags "
                        f"(got {wc.class_tag!r})"
                    )
                if wc.selector is not None or wc.policy not in ("first", "last"):
                    raise TypeError(
                        f"wildcard policy {wc.policy!r}/selector is not "
                        "lowerable to the device tier"
                    )
                policy = 1 if wc.policy == "last" else 0
                recs.append(
                    [REC_WILDCARD, app.actor_id(ev.rcv), policy, wc.class_tag]
                    + [0] * (w - 1)
                )
                continue
            src = _actor_or_external(app, ev.snd)
            payload = uid_payload.get(u.id, None)
            if payload is None:
                payload = _msg_row(app, ev.msg, w)
            recs.append([REC_DELIVERY, src, app.actor_id(ev.rcv)] + payload)
        elif isinstance(ev, TimerDelivery):
            rid = app.actor_id(ev.rcv)
            recs.append([REC_TIMER, rid, rid] + _msg_row(app, ev.msg, w))
        # Quiescence / wait markers have no device meaning in replay.
    if len(recs) > max_records:
        raise ValueError(f"expected trace has {len(recs)} records > {max_records}")
    # Records are compact (no mid-sequence REC_NONE holes): the replay
    # kernel's early-exit path terminates at the first zero-kind record,
    # which must therefore only ever be trailing padding. (ValueError, not
    # assert: this guard must survive python -O.)
    if any(r[0] == 0 for r in recs):
        raise ValueError("REC_NONE hole in expected trace records")
    # Rows are kind/a/b/msg; right-pad to the cfg's record width (a
    # record_parents cfg has a trailing parent column, zero here).
    out = np.zeros((max_records, cfg.rec_width), np.int32)
    for i, r in enumerate(recs):
        out[i, : len(r)] = r
    _check_msg_range(cfg, out[:, 3 : 3 + cfg.msg_width])
    return out


# ---------------------------------------------------------------------------
# Lifting device explore traces back to host EventTraces
# ---------------------------------------------------------------------------

def device_trace_to_guide(
    app: DSLApp, records: np.ndarray, trace_len: int
) -> List[Tuple]:
    """Decode a device-recorded trace into a host guide: a list of
    ("ext", op, a, b, msg) / ("deliver", src, dst, msg, is_timer) steps.
    Accepts parent-tracked records (extra trailing column) transparently."""
    guide: List[Tuple] = []
    for i in range(int(trace_len)):
        rec = records[i]
        kind = int(rec[0])
        msg = tuple(int(x) for x in rec[3 : 3 + app.msg_width])
        if kind == REC_NONE:
            continue
        if kind in (REC_DELIVERY, REC_TIMER):
            guide.append(("deliver", int(rec[1]), int(rec[2]), msg, kind == REC_TIMER))
        elif kind >= REC_EXT_BASE:
            guide.append(("ext", kind - REC_EXT_BASE, int(rec[1]), int(rec[2]), msg))
    return guide
