"""Prefix-fork replay: snapshot device state at branch points, fork lanes.

Every device lane used to replay its schedule from step 0 even though the
dominant workloads are trials that share long common prefixes by
construction: a DPOR backtrack prescription is "the executed prefix plus
one flipped racing delivery", and a ``BatchedDDMin`` /
``BatchedInternalMinimizer`` level's candidates are identical up to the
first removed index. Parsimonious Optimal DPOR (PAPERS.md) gets its
asymptotic win precisely from not re-exploring shared prefixes; the O(1)
autoregressive-caching line of work is the same insight applied to
accelerator state: checkpoint once, fork many.

Device side: ``ScheduleState`` is a fixed-shape NamedTuple, so a snapshot
IS the state. A trunk lane executes the shared prefix once
(``make_replay_prefix_runner`` / ``make_explore_prefix_runner`` /
``make_dpor_prefix_runner``); the ``start_state=``-built kernels broadcast
the snapshot across the lane axis (``vmap(in_axes=None)`` — no per-lane
copy is materialized) and resume with per-lane divergence: remaining
replay records, the full prescription plus the trunk's committed cursor,
or a fresh per-lane rng. Forked results are bit-exact vs scratch because
(a) the trunk replays exactly what a scratch lane's prefix would have and
(b) rng is never consumed before the fork point — injection steps and
prescription-following dispatch never split it (explore.make_step_fn
commits the split only on dispatch steps; prescribed deliveries bypass
the random chooser entirely). The DPOR trunk FREEZES (bit-exact no-op)
the moment no remaining prefix record matches, so the fork lanes redo
that step's decision with the full prescription and their own rng.

Host side: ``PrefixPlanner`` groups a batch of trials by longest common
prefix, bucketed to multiples of ``bucket`` rows so trunk/fork shapes
stay static (a ddmin level's candidates land one group per
first-divergence bucket); ``PrefixCache`` LRU-keeps packed snapshots
keyed by prefix hash so consecutive ddmin levels and DPOR rounds reuse
trunks across kernel launches.

Everything is opt-in: ``DEMI_PREFIX_FORK=1`` / ``--prefix-fork`` (or the
explicit ``prefix_fork=True`` constructor args). With it off, kernels are
built without the ``start_state`` input and their lowering is
byte-identical to the pre-fork tree.

Hierarchical trunks (``PrefixForker.trunk_hier`` +
``make_replay_prefix_resume_runner``): a trunk-cache miss no longer
replays its full prefix — the nearest cached ancestor trunk (one or more
planner buckets shorter) is resumed over just the remaining rows, so a
miss costs O(bucket) and the PrefixCache becomes a trunk tree shared
across ddmin levels and DPOR rounds. All three drivers derive:
``trunk_hier`` serves the replay checker (suffix-record resume),
``trunk_hier_prescribed`` + ``make_dpor_prefix_resume_runner`` serve
``DeviceDPOR`` (the freeze semantics make the ancestor's end state
exactly the longer trunk's state at the freeze step, so the resume
re-follows the FULL prescription from the committed cursor), and
``trunk_from`` + ``make_explore_prefix_resume_runner`` serve the sweep
driver (every group trunk resumes the chunk-wide base trunk — the
common injection rows below the first wait — over just its remaining
injection rows).

Telemetry (``fork.*`` series, plus ``dpor.prefix_group_size``): cache
hits/misses, ``fork.trunk_parent_hits`` (misses served by resuming an
ancestor trunk), ``fork.steps_saved`` (prefix steps the fork lanes did
NOT re-execute, net of the trunk's own run on a cache miss), and
group-size histograms — the signal the tuner's ``calibrate_fork`` axis
(demi_tpu/tune) uses to learn the bucket granularity.
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..dsl import DSLApp
from ..minimization.pipeline import padded_bucket
from . import ops
from .core import (
    REC_NONE,
    ST_DISPATCH,
    ST_DONE,
    ST_INJECT,
    DeviceConfig,
    ScheduleState,
    init_state,
)


def prefix_fork_enabled(explicit: Optional[bool] = None) -> bool:
    """Resolve the prefix-fork switch: an explicit constructor arg wins,
    otherwise ``DEMI_PREFIX_FORK`` (off by default)."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get("DEMI_PREFIX_FORK", "").strip().lower() in (
        "1", "true", "yes", "on"
    )


class PrefixSnapshot(NamedTuple):
    """A trunk lane's state at the branch point. ``state`` is the whole
    ScheduleState pytree (already fixed-shape); the scalars carry the
    loop position so forked lanes keep scratch-identical budgets."""

    state: ScheduleState
    steps: jnp.ndarray  # int32: fused-loop steps consumed (explore/dpor) /
    #                     records applied (replay)
    cursor: jnp.ndarray  # int32: prescription cursor committed by the trunk
    ignored: jnp.ndarray  # int32: replay ignored-absent count so far
    peeked: jnp.ndarray  # int32: replay peek-enabled count so far


def fork_lanes(snapshot: PrefixSnapshot, keys) -> ScheduleState:
    """Broadcast a trunk snapshot across the lane axis with per-lane rng
    divergence — the materialized form of what the ``start_state=``
    kernels do implicitly via ``vmap(in_axes=None)``."""
    b = keys.shape[0]
    state = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (b,) + x.shape), snapshot.state
    )
    return state._replace(rng=keys)


def prefix_digest(*parts: bytes) -> bytes:
    """Compact cache key for a prefix's raw bytes."""
    h = hashlib.blake2b(digest_size=16)
    for p in parts:
        h.update(p)
    return h.digest()


def pad_pow2(n: int, floor: int = 8) -> int:
    """Power-of-two batch bucket so fork-group launches reuse compiled
    shapes. Delegates to ``pipeline.padded_bucket`` — the ONE bucket
    formula; ``speculation_room``'s free-lane estimate assumes dispatch
    padding matches it exactly."""
    return max(floor, padded_bucket(n))


def padded_size(n: int, mesh=None) -> int:
    """The launch size for a fork group or scratch sub-batch: power-of-two
    bucketed, then rounded to a mesh-axis multiple when sharded — the one
    padding rule all three fork call sites (replay checker, DeviceDPOR,
    sweep driver) share."""
    n = pad_pow2(n)
    if mesh is not None:
        from ..parallel.mesh import pad_batch_to_devices

        n = pad_batch_to_devices(n, mesh)
    return n


# ---------------------------------------------------------------------------
# Trunk runners: execute ONE lane through a shared prefix, capture state
# ---------------------------------------------------------------------------

def make_replay_prefix_runner(app: DSLApp, cfg: DeviceConfig):
    """jitted ``run_prefix(records[R, recw], key) -> PrefixSnapshot``:
    apply the prefix records (compact, REC_NONE-terminated — one static
    shape for every prefix length) on a single trunk lane and capture the
    full replay carry (state + ignored/peeked counters)."""
    from .replay import _replay_cfg, make_replay_apply_fn

    cfg = _replay_cfg(cfg)
    apply_one = make_replay_apply_fn(app, cfg)
    oh = cfg.use_onehot

    def run_prefix(records, key) -> PrefixSnapshot:
        state = init_state(app, cfg, key)
        n_rec = records.shape[0]

        def cond(carry):
            s, _ig, _pk, i = carry
            kind = ops.get_scalar(
                records[:, 0], jnp.minimum(i, n_rec - 1), oh
            )
            return (i < n_rec) & (kind != REC_NONE) & (s.status < ST_DONE)

        def body(carry):
            s, ig, pk, i = carry
            rec = ops.get_row(records, jnp.minimum(i, n_rec - 1), oh)
            s, ig, pk = apply_one(s, ig, pk, rec)
            return (s, ig, pk, i + 1)

        state, ignored, peeked, i = jax.lax.while_loop(
            cond, body, (state, jnp.int32(0), jnp.int32(0), jnp.int32(0))
        )
        return PrefixSnapshot(
            state=state, steps=i, cursor=i, ignored=ignored, peeked=peeked
        )

    return jax.jit(run_prefix)


def make_replay_prefix_resume_runner(app: DSLApp, cfg: DeviceConfig):
    """jitted ``resume_prefix(records[R, recw], snap) -> PrefixSnapshot``:
    extend a cached ancestor trunk by applying only the REMAINING prefix
    records (compact, REC_NONE-terminated) — the hierarchical-trunk step.
    A trunk-cache miss used to replay its full p-row prefix from scratch;
    deriving it from the parent bucket's cached trunk costs O(bucket)
    instead of O(p), turning the PrefixCache into a trunk tree shared
    across ddmin levels and DPOR rounds. Bit-exact vs a scratch trunk:
    record application is deterministic and replay lanes never consume
    rng, so state(parent) + suffix rows == state(full prefix); a parent
    that finished early (status >= ST_DONE mid-prefix) applies zero
    suffix rows, exactly where the scratch run would have stopped."""
    from .replay import _replay_cfg, make_replay_apply_fn

    cfg = _replay_cfg(cfg)
    apply_one = make_replay_apply_fn(app, cfg)
    oh = cfg.use_onehot

    def resume_prefix(records, snap: PrefixSnapshot) -> PrefixSnapshot:
        n_rec = records.shape[0]

        def cond(carry):
            s, _ig, _pk, i = carry
            kind = ops.get_scalar(
                records[:, 0], jnp.minimum(i, n_rec - 1), oh
            )
            return (i < n_rec) & (kind != REC_NONE) & (s.status < ST_DONE)

        def body(carry):
            s, ig, pk, i = carry
            rec = ops.get_row(records, jnp.minimum(i, n_rec - 1), oh)
            s, ig, pk = apply_one(s, ig, pk, rec)
            return (s, ig, pk, i + 1)

        state, ignored, peeked, i = jax.lax.while_loop(
            cond, body, (snap.state, snap.ignored, snap.peeked, jnp.int32(0))
        )
        return PrefixSnapshot(
            state=state, steps=snap.steps + i, cursor=snap.cursor + i,
            ignored=ignored, peeked=peeked,
        )

    return jax.jit(resume_prefix)


def make_explore_prefix_runner(app: DSLApp, cfg: DeviceConfig):
    """jitted ``run_prefix(prog: ExtProgram, key) -> PrefixSnapshot``: run
    the fused step through the initial injection segment (deterministic —
    rng is only consumed on dispatch steps) and stop the moment the lane
    leaves ST_INJECT. Lanes sharing the program rows up to (one past) the
    first wait-like op share this state bit-exactly."""
    from .explore import make_any_step_fn

    step = make_any_step_fn(app, cfg)

    def run_prefix(prog, key) -> PrefixSnapshot:
        state = init_state(app, cfg, key)

        def cond(carry):
            s, i = carry
            return (s.status == ST_INJECT) & (i < cfg.max_steps)

        def body(carry):
            s, i = carry
            return step(s, prog), i + 1

        state, steps = jax.lax.while_loop(
            cond, body, (state, jnp.int32(0))
        )
        return PrefixSnapshot(
            state=state, steps=steps, cursor=jnp.int32(0),
            ignored=jnp.int32(0), peeked=jnp.int32(0),
        )

    return jax.jit(run_prefix)


def make_explore_prefix_base_runner(app: DSLApp, cfg: DeviceConfig):
    """jitted ``run_base(prog, key, op_limit) -> PrefixSnapshot``: run the
    deterministic injection segment through the first ``op_limit``
    external ops only (a traced scalar — one compile serves every limit)
    and stop while the lane is still ST_INJECT. This is the sweep
    driver's chunk-wide BASE trunk: every lane of a chunk shares the
    program rows below the chunk's common-prefix/first-wait cap, so the
    base runs once and each group trunk derives from it by resuming over
    just its remaining injection rows (``make_explore_prefix_resume_runner``)
    instead of replaying the whole shared segment per group."""
    from .explore import make_any_step_fn

    step = make_any_step_fn(app, cfg)

    def run_base(prog, key, op_limit) -> PrefixSnapshot:
        state = init_state(app, cfg, key)

        def cond(carry):
            s, i = carry
            return (
                (s.status == ST_INJECT)
                & (s.ext_cursor < op_limit)
                & (i < cfg.max_steps)
            )

        def body(carry):
            s, i = carry
            return step(s, prog), i + 1

        state, steps = jax.lax.while_loop(
            cond, body, (state, jnp.int32(0))
        )
        return PrefixSnapshot(
            state=state, steps=steps, cursor=jnp.int32(0),
            ignored=jnp.int32(0), peeked=jnp.int32(0),
        )

    return jax.jit(run_base)


def make_explore_prefix_resume_runner(app: DSLApp, cfg: DeviceConfig):
    """jitted ``resume_prefix(prog, snap) -> PrefixSnapshot``: continue a
    base trunk's injection segment to the group boundary (the moment the
    lane leaves ST_INJECT). Bit-exact vs a scratch group trunk: injection
    is deterministic and never consumes rng, and the base stopped with
    the lane still ST_INJECT below every member's first wait-like op, so
    state(base) + remaining injections == state(full segment). A base
    that overflowed mid-prefix resumes zero steps — exactly where the
    scratch run would have stopped."""
    from .explore import make_any_step_fn

    step = make_any_step_fn(app, cfg)

    def resume_prefix(prog, snap: PrefixSnapshot) -> PrefixSnapshot:
        def cond(carry):
            s, i = carry
            return (s.status == ST_INJECT) & (i < cfg.max_steps)

        def body(carry):
            s, i = carry
            return step(s, prog), i + 1

        state, steps = jax.lax.while_loop(
            cond, body, (snap.state, snap.steps)
        )
        return PrefixSnapshot(
            state=state, steps=steps, cursor=jnp.int32(0),
            ignored=jnp.int32(0), peeked=jnp.int32(0),
        )

    return jax.jit(resume_prefix)


def _dpor_prefix_loop(app: DSLApp, cfg: DeviceConfig):
    """The prescription-following trunk loop shared by the DPOR prefix
    runner and its hierarchical resume twin: follow the prescription
    (injection steps included) and FREEZE — a bit-exact no-op, state and
    cursor untouched — the first time no remaining prescribed record
    matches the pool. Returns ``run(prog, presc, state, cursor, steps)``
    carrying the loop from any starting carry."""
    from .dpor_sweep import make_prescribed_dispatch
    from .explore import make_step_fn

    assert cfg.record_trace and cfg.record_parents
    base_step = make_step_fn(app, cfg)
    pdispatch = make_prescribed_dispatch(app, cfg)

    def run(prog, presc, state, cursor, steps):
        def cond(carry):
            s, _cur, i, frozen = carry
            return (s.status < ST_DONE) & ~frozen & (i < cfg.max_steps)

        def body(carry):
            s, cur, i, _frozen = carry
            in_dispatch = s.status == ST_DISPATCH

            def dispatch_side(args):
                s, cur = args
                ns, ncur, found = pdispatch(s, presc, cur)
                out = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(found, b, a), s, ns
                )
                return out, jnp.where(found, ncur, cur), ~found

            def inject_side(args):
                s, cur = args
                return base_step(s, prog), cur, jnp.bool_(False)

            ns, ncur, froze = jax.lax.cond(
                in_dispatch, dispatch_side, inject_side, (s, cur)
            )
            # A frozen "step" took no action: don't charge the budget.
            return ns, ncur, i + (~froze).astype(jnp.int32), froze

        state, cursor, steps, _ = jax.lax.while_loop(
            cond, body, (state, cursor, steps, jnp.bool_(False))
        )
        return state, cursor, steps

    return run


def make_dpor_prefix_runner(app: DSLApp, cfg: DeviceConfig):
    """jitted ``run_prefix(prog, presc[R, recw], key) -> PrefixSnapshot``:
    follow the prefix prescription (injection steps included) and FREEZE —
    a bit-exact no-op, state and cursor untouched — the first time no
    remaining prefix record matches the pool. A scratch lane would decide
    that step by scanning the full prescription (and possibly falling back
    to its rng); the fork lanes redo exactly that from the snapshot, so
    stopping before the decision is what keeps parity exact."""
    loop = _dpor_prefix_loop(app, cfg)

    def run_prefix(prog, presc, key) -> PrefixSnapshot:
        state = init_state(app, cfg, key)
        state, cursor, steps = loop(
            prog, presc, state, jnp.int32(0), jnp.int32(0)
        )
        return PrefixSnapshot(
            state=state, steps=steps, cursor=cursor,
            ignored=jnp.int32(0), peeked=jnp.int32(0),
        )

    return jax.jit(run_prefix)


def make_dpor_prefix_resume_runner(app: DSLApp, cfg: DeviceConfig):
    """jitted ``resume_prefix(prog, presc[R, recw], snap) -> PrefixSnapshot``:
    extend a cached ancestor DPOR trunk over the REMAINING prescribed
    records — the prescribed-resume (hierarchical) trunk step. Unlike the
    replay twin, the resume takes the FULL trunk prescription, not just
    the suffix rows: the ancestor's committed cursor points into it, and
    the prescribed-dispatch scan must restart from that cursor (records
    between the cursor and the ancestor's prefix end were absent at the
    freeze point, but the scan that decides the next delivery considers
    them together with the new rows).

    Bit-exact vs a scratch full-prefix trunk: the ancestor froze exactly
    at the first step where none of ITS rows matched, with state/cursor
    untouched by the freeze. A scratch trunk over the longer prescription
    behaves identically up to that step (the scans agree wherever the
    shorter prescription still had a match), and at it scans the extra
    rows — which is exactly what re-entering the loop from the ancestor's
    carry with the full prescription and a cleared freeze flag does. The
    resume therefore costs O(remaining rows) device steps instead of
    O(prefix)."""
    loop = _dpor_prefix_loop(app, cfg)

    def resume_prefix(prog, presc, snap: PrefixSnapshot) -> PrefixSnapshot:
        state, cursor, steps = loop(
            prog, presc, snap.state, snap.cursor, snap.steps
        )
        return PrefixSnapshot(
            state=state, steps=steps, cursor=cursor,
            ignored=snap.ignored, peeked=snap.peeked,
        )

    return jax.jit(resume_prefix)


# ---------------------------------------------------------------------------
# Host-side planning: group trials by bucketed longest common prefix
# ---------------------------------------------------------------------------

class PrefixGroup(NamedTuple):
    prefix_len: int  # shared rows (a multiple of the planner bucket)
    indices: List[int]  # batch positions sharing the prefix
    key: bytes  # digest of the shared prefix rows (cache key)


class PrefixPlanner:
    """Group a batch of trials (row-compact int32 record arrays) by
    longest common prefix, bucketed to multiples of ``bucket`` rows so
    trunk/fork shapes stay static.

    ``plan(records[n, R, w], lengths[n])`` returns ``(groups, scratch)``:
    each group's members share ``records[:, :prefix_len]`` byte-exactly;
    trials with no shareable prefix (divergence inside bucket 0) land in
    ``scratch``. Recursion only descends while a chunk-partition keeps at
    least ``min_group`` members together, so a ddmin level's candidates —
    identical up to the first removed index — come out as one group per
    first-divergence bucket.

    ``plan`` partitions by ARRAY prefix-comparison: every bucket chunk of
    the stacked row matrix is content-hashed in one vectorized pass (the
    128-bit scheme of ``native.prescription_digests``' family), and each
    recursion level is a lexsort + boundary scan over those hashes — no
    per-trial ``tobytes`` in the loop. ``plan_reference`` keeps the
    original per-chunk-bytes recursion as the parity baseline
    (tests/test_host_path.py pins group-for-group equality)."""

    def __init__(self, bucket: int = 8, min_group: int = 2):
        if bucket < 1:
            raise ValueError(f"bucket must be >= 1, got {bucket}")
        self.bucket = bucket
        self.min_group = min_group

    def plan(
        self, records: np.ndarray, lengths: Sequence[int]
    ) -> Tuple[List[PrefixGroup], List[int]]:
        records = np.asarray(records)
        lengths = np.asarray(lengths)
        n, rmax = records.shape[0], records.shape[1]
        groups: List[PrefixGroup] = []
        scratch: List[int] = []
        if n == 0:
            return groups, scratch
        depth_max = rmax // self.bucket
        # Per-(trial, depth) 2x64-bit chunk content hashes, one
        # vectorized pass over the raw bytes (dtype-agnostic, byte-exact
        # like the reference's tobytes comparison, modulo 128-bit
        # collision odds — the trust level of the blake2b-16 trunk keys).
        if depth_max > 0:
            from ..native.analysis import _mix64, _COL_MULT, _SALTS

            flat = np.ascontiguousarray(records[:, : depth_max * self.bucket])
            nbytes = self.bucket * int(
                np.prod(flat.shape[2:], dtype=np.int64)
            ) * flat.dtype.itemsize
            chunks = flat.view(np.uint8).reshape(n, depth_max, nbytes)
            col_pow = np.ones(nbytes, np.uint64)
            if nbytes > 1:
                col_pow[1:] = _COL_MULT
            col_pow = np.cumprod(col_pow)[::-1]
            cv = (chunks.astype(np.uint64) * col_pow[None, None, :]).sum(
                axis=2, dtype=np.uint64
            )
            h1 = _mix64(cv ^ _SALTS[0])
            h2 = _mix64(cv ^ _SALTS[1])
        full_at = lengths[:, None] >= (
            np.arange(1, depth_max + 1, dtype=np.int64) * self.bucket
        )[None, :] if depth_max else np.zeros((n, 0), bool)

        def emit(idx: np.ndarray, depth: int) -> None:
            if depth == 0:
                scratch.extend(int(i) for i in idx)
                return
            p = depth * self.bucket
            groups.append(
                PrefixGroup(
                    prefix_len=p,
                    indices=[int(i) for i in idx],
                    key=prefix_digest(records[idx[0], :p].tobytes()),
                )
            )

        def split(idx: np.ndarray, depth: int) -> None:
            if depth >= depth_max:
                emit(idx, depth)
                return
            full = full_at[idx, depth]
            deeper, rest = idx[full], idx[~full]
            small = [rest]
            if deeper.size:
                k1, k2 = h1[deeper, depth], h2[deeper, depth]
                order = np.lexsort((k2, k1))
                sd, s1, s2 = deeper[order], k1[order], k2[order]
                breaks = np.flatnonzero(
                    (s1[1:] != s1[:-1]) | (s2[1:] != s2[:-1])
                ) + 1
                bounds = np.concatenate(([0], breaks, [sd.size]))
                for lo, hi in zip(bounds[:-1], bounds[1:]):
                    sub = sd[lo:hi]
                    if sub.size >= self.min_group:
                        split(np.sort(sub), depth + 1)
                    else:
                        small.append(sub)
            rest = np.concatenate(small) if len(small) > 1 else rest
            if rest.size:
                emit(np.sort(rest), depth)

        split(np.arange(n, dtype=np.int64), 0)
        return groups, scratch

    def plan_reference(
        self, records: np.ndarray, lengths: Sequence[int]
    ) -> Tuple[List[PrefixGroup], List[int]]:
        """The original per-chunk-bytes recursion — the parity baseline
        for the vectorized ``plan`` (groups are compared as
        (prefix_len, member-set, key) sets; member ORDER within a group
        is load-free: fork results merge by batch index and per-lane
        keys follow batch position)."""
        records = np.asarray(records)
        lengths = np.asarray(lengths)
        groups: List[PrefixGroup] = []
        scratch: List[int] = []

        def chunk_key(i: int, depth: int) -> bytes:
            lo = depth * self.bucket
            return records[i, lo: lo + self.bucket].tobytes()

        def emit(idxs: List[int], depth: int) -> None:
            if depth == 0:
                scratch.extend(idxs)
                return
            p = depth * self.bucket
            groups.append(
                PrefixGroup(
                    prefix_len=p,
                    indices=list(idxs),
                    key=prefix_digest(records[idxs[0], :p].tobytes()),
                )
            )

        def split(idxs: List[int], depth: int) -> None:
            deeper: Dict[bytes, List[int]] = {}
            rest: List[int] = []
            for i in idxs:
                # Only descend through FULL chunks: a trial ending inside
                # the next chunk forks at the current boundary instead of
                # grouping on padding bytes.
                if lengths[i] >= (depth + 1) * self.bucket:
                    deeper.setdefault(chunk_key(i, depth), []).append(i)
                else:
                    rest.append(i)
            for sub in deeper.values():
                if len(sub) >= self.min_group:
                    split(sub, depth + 1)
                else:
                    rest.extend(sub)
            if rest:
                emit(rest, depth)

        split(list(range(records.shape[0])), 0)
        return groups, scratch


class PrefixCache:
    """LRU of packed trunk snapshots keyed by prefix hash. Entries are
    ``(PrefixSnapshot, trunk_steps)``; one snapshot is a single lane's
    state (a few pool-sized arrays), so a few dozen stay cheap while
    letting consecutive ddmin levels / DPOR rounds reuse trunks across
    kernel launches."""

    def __init__(self, capacity: int = 32):
        self.capacity = capacity
        self._entries: "OrderedDict[bytes, Tuple[PrefixSnapshot, int]]" = (
            OrderedDict()
        )
        self.hits = 0
        self.misses = 0

    def get(self, key: bytes) -> Optional[Tuple[PrefixSnapshot, int]]:
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return entry

    def peek(self, key: bytes) -> Optional[Tuple[PrefixSnapshot, int]]:
        """Lookup WITHOUT hit/miss accounting — used by the hierarchical
        ancestor search, whose probes are derivation opportunities, not
        trunk requests (they would otherwise skew the hit rate the tuner
        reads). A found ancestor still refreshes its LRU position."""
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
        return entry

    def put(self, key: bytes, snapshot: PrefixSnapshot, steps: int) -> None:
        self._entries[key] = (snapshot, steps)
        self._entries.move_to_end(key)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)

    def __contains__(self, key: bytes) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)


class PrefixForker:
    """Planner + cache + trunk-runner glue shared by the replay checker,
    ``DeviceDPOR``, and the sweep driver's chunked mode. ``runner`` is a
    jitted trunk runner returning a PrefixSnapshot; statistics accumulate
    in ``stats`` (always) and the ``fork.*`` obs series (when telemetry
    is on)."""

    def __init__(
        self,
        runner: Callable[..., PrefixSnapshot],
        bucket: int = 8,
        capacity: int = 32,
        min_group: int = 2,
        driver: str = "replay",
        resume_runner: Optional[Callable[..., PrefixSnapshot]] = None,
        anchor_stride: Optional[int] = None,
    ):
        self.planner = PrefixPlanner(bucket=bucket, min_group=min_group)
        self.cache = PrefixCache(capacity)
        self.runner = runner
        # Hierarchical trunks: ``resume_runner(suffix_records, snapshot)``
        # extends a cached ancestor trunk by only the remaining rows; with
        # it unset, every cache miss replays its full prefix (the pre-
        # hierarchical behavior, still used by the DPOR/sweep drivers).
        self.resume_runner = resume_runner
        # Anchor-chained trunk building (DPOR cross-round reuse): with
        # ``anchor_stride`` set (in planner buckets), a full-prefix miss
        # is built as a CHAIN of resumes that caches a snapshot at every
        # stride boundary along the way. Round prefixes are round-unique
        # at full length (the PR 6 ~0% reuse finding), but consecutive
        # rounds' racing families share long ancestors — the anchors are
        # exactly the sub-bucket keys those ancestors hit, so a later
        # round's trunk derives in O(remaining rows past the shared
        # anchor) instead of O(prefix). Same total prefix steps as one
        # straight run, plus one launch per stride boundary.
        self.anchor_stride = anchor_stride
        self.driver = driver
        self.stats = {
            "groups": 0,
            "forked_lanes": 0,
            "scratch_lanes": 0,
            "prefix_hits": 0,
            "prefix_misses": 0,
            "parent_trunks": 0,
            "steps_saved": 0,
        }
        # steps_saved terms awaiting a host pull: (trunk-steps scalar,
        # multiplier). Resolving a fresh trunk's steps immediately would
        # block async dispatch, so terms accumulate and are pulled lazily
        # (next plan() or stats_view()) — by then the trunk has long run.
        self._deferred: List[Tuple[object, int]] = []

    def plan(self, records, lengths):
        self.resolve_deferred()
        return self.planner.plan(records, lengths)

    def should_fork(self, group: PrefixGroup) -> bool:
        """Fork when the trunk amortizes: a real shared prefix and either
        enough members or an already-cached trunk (free reuse)."""
        return group.prefix_len > 0 and self.amortizes(
            len(group.indices), group.key
        )

    def amortizes(self, n: int, key: bytes) -> bool:
        """The trunk-amortization rule shared by every fork call site
        (the sweep driver groups by exact digest rather than PrefixGroup,
        so it applies this directly)."""
        return n >= self.planner.min_group or key in self.cache

    def trunk(self, key: bytes, *args) -> Tuple[PrefixSnapshot, object, bool]:
        """Cached trunk snapshot: ``(snapshot, trunk_steps, cache_hit)``.
        ``trunk_steps`` stays a device scalar on a fresh miss (pulling it
        here would block async dispatch); it is only read host-side when
        the deferred steps_saved terms resolve."""
        entry = self.cache.get(key)
        if entry is not None:
            self.stats["prefix_hits"] += 1
            obs.counter("fork.prefix_hits").inc(driver=self.driver)
            return entry[0], entry[1], True
        snapshot = self.runner(*args)
        self.cache.put(key, snapshot, snapshot.steps)
        self.stats["prefix_misses"] += 1
        obs.counter("fork.prefix_misses").inc(driver=self.driver)
        return snapshot, snapshot.steps, False

    def trunk_hier(
        self, key: bytes, trunk_records, rng_key, prefix_len: int
    ) -> Tuple[PrefixSnapshot, object, bool]:
        """``trunk`` with hierarchical derivation: on a cache miss, walk
        the prefix down one planner bucket at a time looking for a cached
        ancestor trunk, and derive the missing trunk by resuming it over
        only the remaining rows (O(bucket) instead of O(prefix)). The
        derived snapshot is cached under the full key, so the PrefixCache
        becomes a trunk TREE: a deep ddmin level's trunk forks off the
        previous level's, which forked off the one before it."""
        if self.resume_runner is None or key in self.cache:
            return self.trunk(key, trunk_records, rng_key)
        b = self.planner.bucket
        for q in range(prefix_len - b, 0, -b):
            parent = self.cache.peek(
                prefix_digest(trunk_records[:q].tobytes())
            )
            if parent is None:
                continue
            suffix = np.zeros_like(trunk_records)
            suffix[: prefix_len - q] = trunk_records[q:prefix_len]
            snapshot = self.resume_runner(suffix, parent[0])
            self.cache.put(key, snapshot, snapshot.steps)
            self._note_parent_trunk(parent)
            return snapshot, snapshot.steps, False
        return self.trunk(key, trunk_records, rng_key)

    def trunk_hier_prescribed(
        self, key: bytes, prog, trunk_records, rng_key, prefix_len: int
    ) -> Tuple[PrefixSnapshot, object, bool]:
        """``trunk_hier`` for prescription-following trunks (DeviceDPOR):
        same ancestor walk, but the resume re-follows the FULL trunk
        prescription from the ancestor's committed cursor (freeze
        semantics — see ``make_dpor_prefix_resume_runner``) instead of a
        compacted suffix, so the runner/resume argument shapes are
        (prog, presc, key) / (prog, presc, snap).

        With ``anchor_stride`` set, the build additionally CACHES
        intermediate snapshots at every stride boundary between the
        found ancestor (or scratch) and the full prefix — truncating the
        prescription at a boundary freezes the trunk loop exactly there,
        and resuming the truncation's snapshot with a longer truncation
        is the documented prescribed-resume semantics, so the chain is
        bit-exact vs one straight run (tests/test_fork.py pins it)."""
        if self.resume_runner is None or key in self.cache:
            return self.trunk(key, prog, trunk_records, rng_key)
        b = self.planner.bucket
        parent = None
        parent_q = 0
        for q in range(prefix_len - b, 0, -b):
            entry = self.cache.peek(
                prefix_digest(trunk_records[:q].tobytes())
            )
            if entry is not None:
                parent, parent_q = entry, q
                break
        if self.anchor_stride:
            return self._trunk_anchor_chain(
                key, prog, trunk_records, rng_key, prefix_len,
                parent, parent_q,
            )
        if parent is not None:
            snapshot = self.resume_runner(prog, trunk_records, parent[0])
            self.cache.put(key, snapshot, snapshot.steps)
            self._note_parent_trunk(parent)
            return snapshot, snapshot.steps, False
        return self.trunk(key, prog, trunk_records, rng_key)

    def _trunk_anchor_chain(
        self, key: bytes, prog, trunk_records, rng_key, prefix_len: int,
        parent, parent_q: int,
    ) -> Tuple[PrefixSnapshot, object, bool]:
        """Build a missing trunk as a chain of prescribed resumes,
        caching an anchor snapshot at every ``anchor_stride``-bucket
        boundary (see ``trunk_hier_prescribed``). Starts from the found
        ancestor (``parent`` at ``parent_q`` rows) or scratch."""
        stride = self.planner.bucket * int(self.anchor_stride)
        snap = parent[0] if parent is not None else None
        boundary = (parent_q // stride + 1) * stride
        anchors = 0
        while boundary < prefix_len:
            trunc = np.zeros_like(trunk_records)
            trunc[:boundary] = trunk_records[:boundary]
            akey = prefix_digest(trunk_records[:boundary].tobytes())
            if akey not in self.cache:
                asnap = (
                    self.runner(prog, trunc, rng_key)
                    if snap is None
                    else self.resume_runner(prog, trunc, snap)
                )
                self.cache.put(akey, asnap, asnap.steps)
                snap = asnap
                anchors += 1
            else:
                snap = self.cache.peek(akey)[0]
            boundary += stride
        if anchors:
            self.stats["anchor_trunks"] = (
                self.stats.get("anchor_trunks", 0) + anchors
            )
            obs.counter("fork.anchor_trunks").inc(anchors, driver=self.driver)
        if snap is None:
            return self.trunk(key, prog, trunk_records, rng_key)
        snapshot = self.resume_runner(prog, trunk_records, snap)
        self.cache.put(key, snapshot, snapshot.steps)
        if parent is not None:
            self._note_parent_trunk(parent)
        else:
            self.stats["prefix_misses"] += 1
            obs.counter("fork.prefix_misses").inc(driver=self.driver)
        return snapshot, snapshot.steps, False

    def trunk_from(
        self, key: bytes, parent: Tuple[PrefixSnapshot, object], *args
    ) -> Tuple[PrefixSnapshot, object, bool]:
        """Trunk derived from an EXPLICIT ancestor snapshot (the sweep
        driver's chunk-wide base trunk, which is keyed outside the
        group-digest scheme): cache contract matches ``trunk``; a miss
        resumes the parent over the remaining rows instead of running
        the full prefix."""
        entry = self.cache.get(key)
        if entry is not None:
            self.stats["prefix_hits"] += 1
            obs.counter("fork.prefix_hits").inc(driver=self.driver)
            return entry[0], entry[1], True
        snapshot = self.resume_runner(*args, parent[0])
        self.cache.put(key, snapshot, snapshot.steps)
        self._note_parent_trunk(parent)
        return snapshot, snapshot.steps, False

    def _note_parent_trunk(self, parent) -> None:
        """Shared accounting for a trunk served by ancestor resume: the
        full-key lookup genuinely missed, the ancestor hit is its own
        (cheaper) event, and note_group's steps_saved term — which
        charges the miss as a FULL trunk run — is credited the parent's
        prefix steps so the evidence the fork tuner reads stays unbiased
        for deep hierarchical workloads."""
        self.stats["prefix_misses"] += 1
        self.stats["parent_trunks"] += 1
        obs.counter("fork.prefix_misses").inc(driver=self.driver)
        obs.counter("fork.trunk_parent_hits").inc(driver=self.driver)
        if self.driver == "dpor":
            # The satellite counter report.py's Pipeline block renders
            # next to dpor.inflight_rounds.
            obs.counter("dpor.trunk_parent_hits").inc()
        self._deferred.append((parent[1], 1))

    def note_group(self, size: int, trunk_steps, cache_hit: bool) -> None:
        """Account one fork-group launch: every member skipped the trunk's
        steps; a cache miss pays the trunk once. The steps term is
        deferred (see ``_deferred``)."""
        self.stats["groups"] += 1
        self.stats["forked_lanes"] += size
        self._deferred.append((trunk_steps, size - (0 if cache_hit else 1)))
        obs.histogram("fork.group_size").observe(size, driver=self.driver)

    def note_scratch(self, n: int) -> None:
        self.stats["scratch_lanes"] += n

    def resolve_deferred(self) -> None:
        """Pull any deferred steps_saved terms host-side. Call sites that
        bypass plan() (the sweep driver groups by exact digest) invoke
        this at the START of each round — the previous round's trunks
        have long completed, so the pull costs no dispatch overlap and
        the deferred list stays bounded by one round's groups."""
        if not self._deferred:
            return
        saved = sum(
            int(jax.device_get(steps)) * mult
            for steps, mult in self._deferred
        )
        self._deferred.clear()
        self.stats["steps_saved"] += saved
        obs.counter("fork.steps_saved").inc(saved, driver=self.driver)

    def stats_view(self) -> dict:
        """The statistics dict with every deferred term resolved — what
        the drivers' ``fork_stats`` surfaces."""
        self.resolve_deferred()
        return dict(self.stats)
