"""Batched device DPOR: explore many backtrack points per kernel launch.

The reference explores one interleaving at a time (DPORwHeuristics runs a
full JVM execution per backtrack point). Here a backtrack point is a
*prescription* — a prefix of delivery records plus the flipped event — and
a whole frontier of prescriptions runs as one vmapped batch: each lane
follows its prescription (skipping absent records, divergence-tolerant)
and continues with random exploration; lanes record parent-tracked traces
(DeviceConfig.record_parents), from which the host derives the
happens-before forest and the next round's racing pairs with no
re-execution. SURVEY §7.2 step 7: the racing-pair scan is data-parallel
bit math; only the frontier priority queue stays host-side.

Host path: the default ``host_path='vectorized'`` derives a whole
round's prescriptions in ONE batch-native call
(``native.racing_prescriptions_batch`` — C++ when a compiler exists,
NumPy otherwise) and dedups on vectorized content digests, so the
per-round host share stays small instead of merely hiding under the
double-buffered overlap; ``'legacy'`` keeps the per-lane scan as the
parity baseline. Both are bit-identical (tests/test_host_path.py), and
every DeviceDPOR tracks its ``host_seconds``/``device_seconds`` split
(the ``dpor.host_share`` gauge, bench configs 2/8).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..config import SchedulerConfig
from ..dsl import DSLApp
from ..external_events import ExternalEvent
from ..schedulers.dpor import arvind_distance
from . import ops
from .core import (
    REC_DELIVERY,
    REC_TIMER,
    ST_DISPATCH,
    ST_DONE,
    ST_VIOLATION,
    DeviceConfig,
    ScheduleState,
    check_invariant,
    deliver_index,
    deliverable_mask,
    init_state,
)
from .encoding import lower_program
from .explore import ExtProgram, LaneResult, _finalize, make_step_fn


def make_prescribed_dispatch(app: DSLApp, cfg: DeviceConfig):
    """``prescribed_dispatch(state, presc, cursor) -> (state', cursor',
    found)``: deliver the first matchable prescribed record at/after
    ``cursor`` (skipping absent ones — divergence tolerance), with the
    per-delivery invariant check. Shared by the lane step below and the
    prefix-fork trunk runner (device/fork.py) so the two cannot drift."""
    big = jnp.int32(2**30)
    r_max = cfg.max_steps
    oh = cfg.use_onehot

    def match_record(state: ScheduleState, rec):
        is_timer_rec = rec[0] == REC_TIMER
        mask = deliverable_mask(state, cfg)
        exact = (
            (state.pool_dst == rec[2])
            & jnp.all(state.pool_msg == rec[3 : 3 + cfg.msg_width][None, :], axis=1)
            & (state.pool_timer == is_timer_rec)
            & (is_timer_rec | (state.pool_src == rec[1]))
        )
        match = mask & exact
        seqs = jnp.where(match, state.pool_seq, big)
        idx = jnp.argmin(seqs).astype(jnp.int32)
        return jnp.where(jnp.any(match), idx, jnp.int32(cfg.pool_capacity))

    def prescribed_dispatch(state: ScheduleState, presc, cursor):
        # Skip past absent prescribed records to the first matchable one.
        def cond(c3):
            c, idx, _ = c3
            rec_kind = ops.get_scalar(
                presc[:, 0], jnp.minimum(c, r_max - 1), oh
            )
            in_range = (c < r_max) & (
                (rec_kind == REC_DELIVERY) | (rec_kind == REC_TIMER)
            )
            return in_range & (idx >= cfg.pool_capacity)

        def body(c3):
            c, _, skips = c3
            idx = match_record(
                state, ops.get_row(presc, jnp.minimum(c, r_max - 1), oh)
            )
            found = idx < cfg.pool_capacity
            return (
                jnp.where(found, c, c + 1),
                idx,
                skips + jnp.where(found, 0, 1),
            )

        c, idx, _ = jax.lax.while_loop(
            cond, body, (cursor, jnp.int32(cfg.pool_capacity), jnp.int32(0))
        )
        found = idx < cfg.pool_capacity
        new_state = deliver_index(state, cfg, app, idx)
        # Per-delivery invariant checks apply during prefix replay too
        # (transient violations — e.g. two-leaders healed by a later
        # step-down — are exactly what DPOR prescribes its way into).
        if cfg.invariant_interval:
            code = jnp.where(
                found, check_invariant(new_state, app), jnp.int32(0)
            )
            new_state = new_state._replace(
                status=jnp.where(
                    code != 0, jnp.int32(ST_VIOLATION), new_state.status
                ),
                violation=jnp.where(
                    code != 0, code.astype(jnp.int32), new_state.violation
                ),
            )
        return new_state, jnp.where(found, c + 1, c), found

    return prescribed_dispatch


def make_dpor_run_lane(app: DSLApp, cfg: DeviceConfig):
    """Unjitted single-lane DPOR sweep ``run_lane(prog, prescription, key,
    start_state=None) -> LaneResult`` (composable with vmap/jit by callers
    — the XLA kernel below and the pallas twin in pallas_explore.py).
    cfg must have record_trace and record_parents on.

    Dispatch follows the prescription while records match (absent records
    are skipped — divergence tolerance), then falls back to the explore
    step's random choice. ``start_state`` (a device/fork.py
    PrefixSnapshot) resumes from a trunk's state + committed cursor with
    this lane's own rng; the default None keeps today's lowering
    byte-identical."""
    assert cfg.record_trace and cfg.record_parents
    base_step = make_step_fn(app, cfg)
    r_max = cfg.max_steps
    recw = cfg.rec_width
    prescribed_dispatch = make_prescribed_dispatch(app, cfg)

    def step(carry, presc, prog):
        state, cursor = carry

        oh = cfg.use_onehot

        in_dispatch = state.status == ST_DISPATCH
        rec_kind = ops.get_scalar(
            presc[:, 0], jnp.minimum(cursor, r_max - 1), oh
        )
        presc_active = in_dispatch & (cursor < r_max) & (
            (rec_kind == REC_DELIVERY) | (rec_kind == REC_TIMER)
        )

        def with_prescription(args):
            state, cursor = args
            new_state, new_cursor, found = prescribed_dispatch(
                state, presc, cursor
            )
            # If nothing in the prescription matched, fall back to the
            # normal (random) step from the ORIGINAL state.
            fell_back = ~found
            rnd = base_step(state, prog)
            out = jax.tree_util.tree_map(
                lambda a, b: jnp.where(fell_back, a, b), rnd, new_state
            )
            return out, new_cursor

        def without(args):
            state, cursor = args
            return base_step(state, prog), cursor

        state, cursor = jax.lax.cond(
            presc_active, with_prescription, without, (state, cursor)
        )
        return (state, cursor), None

    def run_lane(prog: ExtProgram, presc, key, start_state=None) -> LaneResult:
        if start_state is None:
            state = init_state(app, cfg, key)
            cursor0 = jnp.int32(0)
            (state, _cursor), _ = jax.lax.scan(
                lambda carry, _: step(carry, presc, prog),
                (state, cursor0), None, length=cfg.max_steps,
            )
        else:
            # Forked lane: the trunk delivered the shared-prefix records
            # (rng untouched — prescribed dispatch never splits it), so
            # resuming with this lane's key and the remaining step budget
            # is bit-identical to a scratch lane. Frozen lanes' steps are
            # no-ops, so the while_loop matches the fixed-length scan.
            state = start_state.state._replace(rng=key)

            def cond(carry):
                (s, _cur), i = carry
                return (s.status < ST_DONE) & (i < cfg.max_steps)

            def body(carry):
                sc, i = carry
                sc, _ = step(sc, presc, prog)
                return sc, i + 1

            (state, _cursor), _ = jax.lax.while_loop(
                cond, body,
                ((state, start_state.cursor), start_state.steps),
            )
        state = jax.lax.cond(
            state.status < ST_DONE, lambda s: _finalize(s, app, cfg), lambda s: s, state
        )
        return LaneResult(
            status=state.status,
            violation=state.violation,
            deliveries=state.deliveries,
            trace=state.trace,
            trace_len=state.trace_len,
            sched_hash=state.sched_hash,
        )

    return run_lane


class DporSleepResult(NamedTuple):
    """LaneResult plus the device-encoded sleep-set observations (the
    sleep-kernel return type; leading fields mirror LaneResult so every
    existing consumer reads it unchanged)."""

    status: jnp.ndarray
    violation: jnp.ndarray
    deliveries: jnp.ndarray
    trace: jnp.ndarray
    trace_len: jnp.ndarray
    sched_hash: jnp.ndarray
    # Per sleeping row: first at-or-after-node delivery ordinal whose
    # record was dependent with (or content-identical to) it —
    # BIG_ORDINAL = still asleep at lane end.
    sleep_wake: jnp.ndarray  # [sleep_cap] int32
    # First at-or-after-node ordinal that delivered a still-sleeping
    # row (the redundant-suffix marker; BIG_ORDINAL = never).
    sleep_slept: jnp.ndarray  # int32


def make_dpor_sleep_run_lane(
    app: DSLApp, cfg: DeviceConfig, sleep_cap: int, commute_matrix=None
):
    """The sleep-set twin of ``make_dpor_run_lane``: same lane semantics
    bit-for-bit (state, cursor, and rng math are shared — LaneResult
    fields are identical to the plain kernel's), plus per-step wake
    tracking over a bounded block of sleeping records.

    ``run_lane(prog, presc, key, sleep_rows[S, recw], sleep_from,
    start_state=None) -> DporSleepResult``. Tracking applies to
    deliveries at ordinals >= ``sleep_from`` — the NODE ordinal, i.e.
    the length of the lane's identity prescription (prefix + flip);
    rows before it are the path TO the node the sleep rows attach at,
    so they neither wake nor trip them, while the wakeup-sequence guide
    rows beyond it are ordinary tracked deliveries. A tracked delivery
    wakes every sleeping row it is dependent with — same receiver and
    not proven commuting by ``commute_matrix`` (the
    ``StaticIndependence.device_matrix()`` baked in as a kernel
    constant), or content-identical — and a tracked delivery content-
    identical to a still-sleeping row marks the redundant suffix.
    Forked lanes resume with wake state intact because ordinals are
    absolute (``state.deliveries`` rides the snapshot) and the fork
    planner clamps trunk prefixes below every member's node under
    sleep mode, so the pre-fork segment is entirely untracked.

    Why the fixed ``sleep_from`` ordinal is safe against divergence
    (prescribed rows skipped would otherwise shift the real node
    earlier and leave a wake window untracked — unsound over-pruning):
    a DERIVED prescription's identity is its source lane's own
    delivered records plus a co-enabled flip, both of which replay
    deterministically from init (prescribed dispatch never consumes
    rng, injections are deterministic, and the matcher's lowest-seq
    pick is a function of state alone) — so the first ``sleep_from``
    deliveries cannot diverge. The only divergence-prone prescriptions
    are host-lowered SEEDS and post-node guide rows; seeds carry no
    sleep rows, and guide rows sit at ordinals >= ``sleep_from`` where
    tracking is already on."""
    from ..analysis.sleep import BIG_ORDINAL

    assert cfg.record_trace and cfg.record_parents
    base_step = make_step_fn(app, cfg)
    prescribed_dispatch = make_prescribed_dispatch(app, cfg)
    r_max = cfg.max_steps
    recw = cfg.rec_width
    oh = cfg.use_onehot
    big = jnp.int32(BIG_ORDINAL)
    mat = (
        None
        if commute_matrix is None
        else jnp.asarray(np.asarray(commute_matrix), jnp.int32)
    )

    def wake_update(old_state, new_state, sleep_from, sleep_rows, wake,
                    slept):
        delivered = new_state.deliveries > old_state.deliveries
        ordv = old_state.deliveries  # this delivery's absolute ordinal
        row = ops.get_row(
            new_state.trace, jnp.maximum(new_state.trace_len - 1, 0), oh
        )
        valid = sleep_rows[:, 0] != 0
        same_dst = sleep_rows[:, 2] == row[2]
        content_eq = (
            (sleep_rows[:, 0] == row[0])
            & same_dst
            & jnp.all(
                sleep_rows[:, 3: recw - 2] == row[3: recw - 2][None, :],
                axis=1,
            )
            & ((row[0] == REC_TIMER) | (sleep_rows[:, 1] == row[1]))
        )
        if mat is None:
            dep = same_dst
        else:
            m = mat.shape[0]
            tr, ts = row[3], sleep_rows[:, 3]
            ir = jnp.where((tr >= 0) & (tr < m - 1), tr, m - 1)
            isx = jnp.where((ts >= 0) & (ts < m - 1), ts, m - 1)
            dep = same_dst & (mat[ir, isx] == 0)
        dep = dep | content_eq
        asleep = wake >= big
        tracked = delivered & (ordv >= sleep_from)
        wake = jnp.where(tracked & valid & asleep & dep, ordv, wake)
        hit = tracked & jnp.any(valid & asleep & content_eq)
        slept = jnp.where(hit & (slept >= big), ordv, slept)
        return wake, slept

    def step(carry, presc, prog, sleep_rows, sleep_from):
        state, cursor, wake, slept = carry
        in_dispatch = state.status == ST_DISPATCH
        rec_kind = ops.get_scalar(
            presc[:, 0], jnp.minimum(cursor, r_max - 1), oh
        )
        presc_active = in_dispatch & (cursor < r_max) & (
            (rec_kind == REC_DELIVERY) | (rec_kind == REC_TIMER)
        )

        def with_prescription(args):
            state, cursor = args
            new_state, new_cursor, found = prescribed_dispatch(
                state, presc, cursor
            )
            fell_back = ~found
            rnd = base_step(state, prog)
            out = jax.tree_util.tree_map(
                lambda a, b: jnp.where(fell_back, a, b), rnd, new_state
            )
            return out, new_cursor

        def without(args):
            state, cursor = args
            return base_step(state, prog), cursor

        new_state, new_cursor = jax.lax.cond(
            presc_active, with_prescription, without, (state, cursor)
        )
        wake, slept = wake_update(
            state, new_state, sleep_from, sleep_rows, wake, slept
        )
        return (new_state, new_cursor, wake, slept)

    def run_lane(prog, presc, key, sleep_rows, sleep_from, start_state=None):
        wake0 = jnp.full((sleep_cap,), BIG_ORDINAL, jnp.int32)
        if start_state is None:
            carry = (init_state(app, cfg, key), jnp.int32(0), wake0, big)
            carry, _ = jax.lax.scan(
                lambda c, _: (
                    step(c, presc, prog, sleep_rows, sleep_from), None
                ),
                carry, None, length=cfg.max_steps,
            )
            state, _cursor, wake, slept = carry
        else:
            state0 = start_state.state._replace(rng=key)

            def cond(c2):
                (s, *_rest), i = c2
                return (s.status < ST_DONE) & (i < cfg.max_steps)

            def body(c2):
                c, i = c2
                return step(c, presc, prog, sleep_rows, sleep_from), i + 1

            carry, _ = jax.lax.while_loop(
                cond, body,
                ((state0, start_state.cursor, wake0, big),
                 start_state.steps),
            )
            state, _cursor, wake, slept = carry
        state = jax.lax.cond(
            state.status < ST_DONE,
            lambda s: _finalize(s, app, cfg), lambda s: s, state,
        )
        return DporSleepResult(
            status=state.status,
            violation=state.violation,
            deliveries=state.deliveries,
            trace=state.trace,
            trace_len=state.trace_len,
            sched_hash=state.sched_hash,
            sleep_wake=wake,
            sleep_slept=slept,
        )

    return run_lane


def make_dpor_kernel(
    app: DSLApp, cfg: DeviceConfig, start_state: bool = False,
    sleep_cap: int = 0, commute_matrix=None,
):
    """jitted ``kernel(progs[B], prescriptions[B, R, recw], keys[B]) ->
    LaneResult[B]`` (see make_dpor_run_lane). ``start_state=True`` adds a
    fourth argument — a device/fork.py PrefixSnapshot broadcast across the
    lane axis — resuming the whole batch from one trunk's state.
    ``sleep_cap > 0`` builds the sleep-set variant instead: the kernel
    takes an extra ``sleep_rows[B, sleep_cap, recw]`` input and returns
    ``DporSleepResult`` (LaneResult fields are bit-identical to the
    plain kernel's — the wake tracking is observation-only)."""
    if sleep_cap > 0:
        run_sleep = make_dpor_sleep_run_lane(
            app, cfg, sleep_cap, commute_matrix
        )
        if not start_state:
            return jax.jit(
                jax.vmap(run_sleep, in_axes=(0, 0, 0, 0, 0))
            )
        return jax.jit(
            jax.vmap(
                lambda prog, presc, key, srows, sfrom, snap: run_sleep(
                    prog, presc, key, srows, sfrom, snap
                ),
                in_axes=(0, 0, 0, 0, 0, None),
            )
        )
    run_lane = make_dpor_run_lane(app, cfg)
    if not start_state:
        return jax.jit(jax.vmap(run_lane))
    return jax.jit(
        jax.vmap(
            lambda prog, presc, key, snap: run_lane(prog, presc, key, snap),
            in_axes=(0, 0, 0, None),
        )
    )


# ---------------------------------------------------------------------------
# Host-side racing analysis over parent-tracked records
# ---------------------------------------------------------------------------

def racing_prescriptions(
    records: np.ndarray, trace_len: int, rec_width: int,
    independence=None,
) -> List[Tuple[Tuple[int, ...], ...]]:
    """From one lane's parent-tracked trace, derive backtrack prescriptions:
    for each racing pair (i, j) — same receiver, concurrent (no
    happens-before path), j's message already created before i — the
    prescription is the delivery records before i plus j's record.

    This is the LEGACY per-lane surface (one scan call per lane, one
    Python tuple loop per racing pair), kept for the ``host_path='legacy'``
    parity baseline and the randomized parity suite
    (tests/test_host_path.py). The frontier hot path uses
    ``native.racing_prescriptions_batch`` — one call per ROUND — instead;
    see ``DeviceDPOR._process_round``."""
    out, _positions = racing_prescriptions_meta(
        records, trace_len, rec_width, independence=independence
    )
    return [presc for presc, _branch, _flip_ord in out]


def racing_prescriptions_meta(
    records: np.ndarray, trace_len: int, rec_width: int,
    independence=None,
) -> Tuple[List[Tuple[Tuple[Tuple[int, ...], ...], int, int]], np.ndarray]:
    """``racing_prescriptions`` plus the derivation metadata the sleep-
    set admission needs: returns ``([(prescription, branch_ordinal,
    flip_ordinal)], positions)`` where ``branch_ordinal`` is the count
    of deliveries strictly before the race's first delivery
    (== len(prescription) - 1), ``flip_ordinal`` the flipped delivery's
    ordinal in the lane (the wakeup-sequence guide drops it from the
    suffix), and ``positions`` the lane's delivery trace positions
    (prescription prefix row t sits at ``positions[t]`` — the
    own-position input of the canonical class key)."""
    from ..native import racing_pair_scan

    # Slice to rec_width: the scan derives the parent column from the last
    # column, so trailing padding must never reach it.
    recs = records[:trace_len, :rec_width]
    pairs = racing_pair_scan(recs)
    is_delivery = np.isin(recs[:, 0], (REC_DELIVERY, REC_TIMER))
    positions = np.nonzero(is_delivery)[0]
    if len(pairs) == 0:
        return [], positions
    # Record tuples materialized once; prefix for branch index i is the
    # delivery tuples strictly before i.
    tuples = {int(p): tuple(int(x) for x in recs[p]) for p in positions}
    ordered = [int(p) for p in positions]
    out: List[Tuple[Tuple[Tuple[int, ...], ...], int]] = []
    pruned_fungible = pruned_commute = 0
    for i, j in pairs:
        if independence is not None:
            # Same per-pair predicate + placement as the batch paths
            # (analysis.StaticIndependence; fungible checked first), so
            # legacy-vs-vectorized stays bit-identical with pruning on.
            kind = independence.pair_pruned_kind(recs[i], recs[int(j)],
                                                rec_width)
            if kind is not None:
                if kind == "fungible":
                    pruned_fungible += 1
                else:
                    pruned_commute += 1
                if independence.audit:
                    k = np.searchsorted(positions, i)
                    independence.note_pruned_prescription(
                        tuple([tuples[p] for p in ordered[:k]]
                              + [tuples[int(j)]])
                    )
                continue
        k = int(np.searchsorted(positions, i))
        jj = int(np.searchsorted(positions, int(j)))
        prefix = [tuples[p] for p in ordered[:k]]
        prefix.append(tuples[int(j)])
        out.append((tuple(prefix), k, jj))
    if independence is not None:
        independence.note_pruned(pruned_fungible, pruned_commute,
                                 tier="device")
    return out, positions


def _resolve_static_independence(app: DSLApp, explicit=None):
    """Resolve the static-pruning switch into a relation (or None).

    ``explicit`` may be an analysis.StaticIndependence instance (used as
    given — the bench passes audit-mode relations), True (build one from
    the app's handler analysis), False (off), or None (the
    ``DEMI_STATIC_PRUNE`` env flag decides). Off by default: static
    pruning changes which backtracks are derived, so like every
    schedule-space feature here it ships opt-in."""
    from ..analysis import StaticIndependence, static_prune_enabled

    if explicit is not None and not isinstance(explicit, bool):
        return explicit
    if static_prune_enabled(explicit):
        return StaticIndependence.for_app(app)
    return None


def _resolve_sleep_sets(app: DSLApp, explicit=None, independence=None):
    """Resolve the sleep-set switch into an analysis.SleepSets (or None).

    ``explicit`` may be a SleepSets instance (used as given — the bench
    passes observe-/audit-mode objects), True (build one from the app),
    False (off), or None (the ``DEMI_SLEEP_SETS`` env flag decides).
    ``independence`` (a StaticIndependence, when static pruning is also
    on) doubles as the dependence oracle; otherwise one is derived from
    the app purely for dependence — its prune ledger is never consulted.
    Off by default: sleep-set pruning removes whole explored schedules,
    so like every schedule-space feature here it ships opt-in with the
    unpruned path as the pinned A/B baseline."""
    from ..analysis import SleepSets, StaticIndependence, sleep_sets_enabled

    if explicit is not None and not isinstance(explicit, bool):
        return explicit
    if sleep_sets_enabled(explicit):
        rel = (
            independence
            if independence is not None
            else StaticIndependence.for_app(app)
        )
        return SleepSets(independence=rel)
    return None


def _resolve_host_path(explicit: Optional[str] = None) -> str:
    """Resolve the frontier host-path switch: 'vectorized' (default —
    batch-native racing analysis + digest-keyed dedup) or 'legacy' (the
    per-lane scan + per-pair Python tuple loop, kept as the parity
    baseline). An explicit constructor arg wins; ``DEMI_HOST_PATH``
    otherwise (values ``legacy``/``python``/``py`` select the old path)."""
    if explicit is None:
        env = os.environ.get("DEMI_HOST_PATH", "").strip().lower()
        explicit = "legacy" if env in ("legacy", "python", "py") else "vectorized"
    if explicit not in ("vectorized", "legacy"):
        raise ValueError(
            f"host_path must be 'vectorized' or 'legacy', got {explicit!r}"
        )
    return explicit


def _resolve_host_shards(explicit: Optional[int] = None) -> int:
    """Resolve the admission shard count without importing the fleet
    package on every construction: explicit arg wins, then
    ``DEMI_HOST_SHARDS``, default 1 (the sequential pipeline — zero
    sharded machinery is built at 1)."""
    if explicit is not None:
        return max(1, int(explicit))
    try:
        return max(1, int(os.environ.get("DEMI_HOST_SHARDS", "1") or 1))
    except ValueError:
        return 1


class DeviceDPOROracle:
    """TestOracle over DeviceDPOR: systematic batched search for a target
    violation on a given external program; positives lift to full host
    EventTraces via GuidedScheduler (BASELINE config 2 shape: bounded
    DPOR search on raft-class apps).

    Resumable: one DeviceDPOR (frontier + explored set) is kept per
    external subsequence, so repeated DDMin probes of the same subsequence
    continue the search instead of restarting (the device analog of
    ResumableDPOR, IncrementalDeltaDebugging.scala:94-122). With
    ``initial_trace`` set, each fresh instance is seeded with the recorded
    schedule's prescription; ``max_distance`` (set by IncrementalDDMin)
    caps backtracks by edit distance to it.

    One jitted DPOR kernel (and fork kernel) is shared across the
    resumable instances — the kernel closes over (app, cfg) only, the
    program is data — so a DDMin run probing many subsequences compiles
    once instead of once per subsequence.

    Async surface (``async_min``, default the ``DEMI_ASYNC_MIN`` env
    switch): ``supports_async`` + ``test_window`` let the speculative
    minimizers (DDMin's left/right pair batching, LeftToRightRemoval's
    windows) batch a whole window of probes' frontier rounds into one
    device launch (``explore_window``); each probe's instance state
    commits only when its resolver is consulted, so an unconsulted probe
    leaves its resumable frontier exactly as the sequential path would.
    ``double_buffer`` threads through to each instance's in-flight round
    dispatch (see DeviceDPOR)."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        config: SchedulerConfig,
        batch_size: int = 64,
        max_rounds: int = 20,
        initial_trace=None,
        autotune: bool = False,
        prefix_fork: Optional[bool] = None,
        async_min: Optional[bool] = None,
        double_buffer: Optional[bool] = None,
        host_path: Optional[str] = None,
        static_independence=None,
        sleep_sets=None,
    ):
        from ..minimization.pipeline import async_min_enabled
        from .fork import prefix_fork_enabled

        self.app = app
        self.cfg = cfg
        self.config = config
        self.batch_size = batch_size
        self.max_rounds = max_rounds
        self.last_interleavings = 0
        self.initial_trace = initial_trace
        self.prefix_fork = prefix_fork
        self.host_path = host_path
        # One static may-commute relation shared by every resumable
        # instance (the relation is per-app; its prune ledger aggregates
        # across instances — what static_stats reports).
        self.static_independence = _resolve_static_independence(
            app, static_independence
        )
        # Sleep sets: resolved per INSTANCE (class/wakeup state is
        # per-subsequence — prescriptions from different external
        # programs must never class-merge), but the on/off decision and
        # the shared sleep kernels are resolved once here.
        from ..analysis import sleep_cap as _sleep_cap
        from ..analysis import sleep_sets_enabled

        if sleep_sets is not None and not isinstance(sleep_sets, bool):
            # Class/wakeup state is per-subsequence: a single caller
            # SleepSets shared across resumable instances would merge
            # class spaces from different external programs. Refuse
            # loudly instead of silently substituting.
            raise TypeError(
                "DeviceDPOROracle takes sleep_sets as bool/None; "
                "per-instance SleepSets are built internally"
            )
        self.sleep_sets = (
            sleep_sets
            if isinstance(sleep_sets, bool)
            else sleep_sets_enabled(None)
        )
        sleep_matrix = None
        if self.sleep_sets:
            rel = (
                self.static_independence
                if self.static_independence is not None
                else None
            )
            if rel is None:
                from ..analysis import StaticIndependence

                rel = StaticIndependence.for_app(app)
            self._sleep_dependence = rel
            sleep_matrix = rel.device_matrix()
        else:
            self._sleep_dependence = None
        self._sleep_kernel_cap = _sleep_cap() if self.sleep_sets else 0
        self._sleep_matrix = sleep_matrix
        self.max_distance: Optional[int] = None
        # Measurement-guided budget control: each resumable DPOR instance
        # gets its own DporBudgetTuner (frontier dynamics are
        # per-subsequence), fed by the per-round redundant/pruned counts.
        self.autotune = autotune
        self._async = async_min_enabled(async_min)
        self._double_buffer = double_buffer
        # Shared kernels (pallas builds its own per-instance closures;
        # mesh sharding isn't an oracle concern).
        impl = os.environ.get("DEMI_DEVICE_IMPL", "xla")
        self._kernel = (
            make_dpor_kernel(
                app, cfg, sleep_cap=self._sleep_kernel_cap,
                commute_matrix=self._sleep_matrix,
            )
            if impl != "pallas"
            else None
        )
        self._fork_kernel = (
            make_dpor_kernel(
                app, cfg, start_state=True,
                sleep_cap=self._sleep_kernel_cap,
                commute_matrix=self._sleep_matrix,
            )
            if impl != "pallas" and prefix_fork_enabled(prefix_fork)
            else None
        )
        self._instances: Dict[Tuple, DeviceDPOR] = {}

    @property
    def supports_async(self) -> bool:
        """True when the async-minimization pipeline is on — what the
        speculative minimizers probe before using ``test_window``."""
        return self._async

    def set_initial_trace(self, trace) -> None:
        self.initial_trace = trace

    @property
    def fork_stats(self) -> Optional[dict]:
        """Aggregate prefix-fork statistics across the resumable
        instances (None when forking is off) — what the CLI reports."""
        stats = [
            inst._forker.stats_view()
            for inst in self._instances.values()
            if inst._forker is not None
        ]
        if not stats:
            return None
        out: Dict[str, int] = {}
        for s in stats:
            for k, v in s.items():
                out[k] = out.get(k, 0) + v
        return out

    def tuner_summaries(self) -> List[dict]:
        """Public view of each resumable instance's budget-tuner state
        (empty unless ``autotune=True``) — what the CLI reports."""
        return [
            {
                "rounds": inst.tuner.rounds,
                "round_batch": inst.tuner.round_batch,
                "max_distance": inst.tuner.max_distance,
            }
            for inst in self._instances.values()
            if inst.tuner is not None
        ]

    def async_stats(self) -> Dict[str, int]:
        """In-flight round economics summed across the resumable
        instances — what the CLI and bench config 8 report."""
        out = {"inflight_rounds": 0, "inflight_hits": 0, "inflight_waste": 0}
        for inst in self._instances.values():
            for k in out:
                out[k] += inst.async_stats[k]
        return out

    @property
    def static_stats(self) -> Optional[Dict[str, int]]:
        """Static-pruning ledger (None when the relation is off) — what
        the CLI summary and bench report: racing pairs skipped because
        the flip was provably a no-op, by kind."""
        if self.static_independence is None:
            return None
        return dict(self.static_independence.pruned_total)

    @property
    def sleep_stats(self) -> Optional[Dict[str, object]]:
        """Sleep-set ledger summed across the resumable instances (None
        when sleep sets are off) — what the CLI summary reports: prune
        counts by kind, distinct classes, and the aggregate redundancy
        ratio."""
        if not self.sleep_sets:
            return None
        pruned = {"sleep": 0, "class": 0}
        classes = explored = 0
        for inst in self._instances.values():
            if inst.sleep is None:
                continue
            for k, v in inst.sleep.pruned_total.items():
                pruned[k] = pruned.get(k, 0) + v
            classes += len(inst.sleep.classes)
            explored += len(inst.explored)
        return {
            "pruned": pruned,
            "classes": classes,
            "explored": explored,
            "redundancy_ratio": (
                round(explored / classes, 4) if classes else None
            ),
        }

    def host_share(self) -> Optional[float]:
        """Host-vs-device wall-time split summed across the resumable
        instances (None before any round ran) — the CLI summary's
        host-share figure."""
        host = sum(i.host_seconds for i in self._instances.values())
        dev = sum(i.device_seconds for i in self._instances.values())
        total = host + dev
        return host / total if total > 0 else None

    def _instance(self, externals) -> DeviceDPOR:
        key = tuple(e.eid for e in externals)
        inst = self._instances.get(key)
        if inst is None:
            from ..analysis import SleepSets

            inst = DeviceDPOR(
                self.app, self.cfg, externals, self.batch_size,
                prefix_fork=self.prefix_fork,
                double_buffer=self._double_buffer,
                kernel=self._kernel,
                fork_kernel=self._fork_kernel,
                host_path=self.host_path,
                static_independence=(
                    self.static_independence
                    if self.static_independence is not None
                    else False
                ),
                sleep_sets=(
                    SleepSets(
                        independence=self._sleep_dependence,
                        cap=self._sleep_kernel_cap,
                    )
                    if self.sleep_sets
                    else False
                ),
            )
            if self.initial_trace is not None:
                inst.seed(
                    steering_prescription(
                        self.app, self.cfg, self.initial_trace, externals
                    )
                )
            if self.autotune:
                from ..tune import DporBudgetTuner

                inst.tuner = DporBudgetTuner(
                    batch=self.batch_size, max_distance=self.max_distance
                )
            self._instances[key] = inst
        inst.max_distance = self.max_distance
        if inst.tuner is not None:
            # The caller's budget (IncrementalDDMin's growing cap) is the
            # floor; a tuner that widened past it keeps its wider budget.
            inst.tuner.max_distance = (
                self.max_distance
                if inst.tuner.max_distance is None
                else max_distance_union(
                    inst.tuner.max_distance, self.max_distance
                )
            )
            if inst.tuner.max_distance is not None:
                inst.max_distance = inst.tuner.max_distance
        return inst

    @staticmethod
    def _check_fingerprint(violation_fingerprint) -> None:
        if violation_fingerprint is not None and not hasattr(
            violation_fingerprint, "code"
        ):
            # Device verdicts are int codes (same contract as
            # DeviceSTSOracle); don't silently widen unknown fingerprints
            # to accept-anything.
            raise TypeError(
                "DeviceDPOROracle needs an IntViolation-style fingerprint "
                f"(got {type(violation_fingerprint).__name__})"
            )

    def _lift(self, externals, found, violation_fingerprint):
        """Lift a violating device lane to a full host EventTrace via
        GuidedScheduler — the host half of a probe (and the part
        ``test_window`` keeps on-consult, in sequential order)."""
        from ..schedulers.guided import GuidedScheduler, GuideDivergence
        from .encoding import device_trace_to_guide

        records, trace_len = found
        guide = device_trace_to_guide(self.app, records, trace_len)
        gs = GuidedScheduler(self.config, self.app)
        # No per-delivery check needed here: a violating device lane halts
        # at the violation, so the lifted trace's final state carries it.
        try:
            result = gs.execute_guide(guide)
        except GuideDivergence:
            obs.counter("dpor.lift_divergences").inc()
            return None  # device/host mismatch = non-reproduction
        if result.violation is None:
            return None
        if violation_fingerprint is not None and not violation_fingerprint.matches(
            result.violation
        ):
            return None
        result.trace.set_original_externals(list(externals))
        return result.trace

    def test(self, externals, violation_fingerprint, stats=None, init=None):
        if stats is not None:
            stats.record_replay()
        self._check_fingerprint(violation_fingerprint)
        dpor = self._instance(externals)
        target = getattr(violation_fingerprint, "code", None)
        with obs.span(
            "dpor.oracle_probe", externals=len(externals)
        ) as sp:
            found = dpor.explore(
                target_code=target, max_rounds=self.max_rounds
            )
            sp.set(found=found is not None)
        self.last_interleavings = dpor.interleavings
        if found is None:
            return None
        return self._lift(list(externals), found, violation_fingerprint)

    def test_window(self, candidates, violation_fingerprint):
        """One batched window of DPOR probes: per-candidate lazy
        resolvers whose consulted prefix behaves exactly like sequential
        ``test`` calls. The device work — every probe's frontier rounds —
        runs eagerly up front via ``explore_window`` (left and right
        probes' rounds share launches), but each probe's resumable
        instance state (explored set, frontier, interleavings, tuner)
        commits only when its resolver is consulted: the pre-window
        snapshot is restored immediately after exploration, and the
        resolver swaps in the post-window snapshot. A probe the caller
        never consults — DDMin's right half after a left success — leaves
        its instance exactly as the sequential path (which never ran it)
        would have. The host lift stays on-consult, in consult order."""
        self._check_fingerprint(violation_fingerprint)
        target = getattr(violation_fingerprint, "code", None)
        probes: List[tuple] = []
        window: List[DeviceDPOR] = []
        seen_keys = set()
        for ext in candidates:
            key = tuple(e.eid for e in ext)
            if key in seen_keys:
                # Duplicate subsequence in one window: the second probe
                # must observe the first's committed state, which only
                # exists at consult time — resolve it sequentially.
                probes.append((list(ext), None, None))
                continue
            seen_keys.add(key)
            dpor = self._instance(ext)
            probes.append((list(ext), dpor, _dpor_search_state(dpor)))
            window.append(dpor)
        with obs.span("dpor.window", probes=len(window)) as sp:
            founds = explore_window(window, target, self.max_rounds)
            sp.set(found=sum(f is not None for f in founds))
        posts = [_dpor_search_state(d) for d in window]
        by_inst = {id(d): k for k, d in enumerate(window)}
        for _ext, dpor, pre in probes:
            if dpor is not None:
                _dpor_restore_state(dpor, pre)

        def resolver(i: int):
            ext, dpor, _pre = probes[i]
            if dpor is None:
                return self.test(ext, violation_fingerprint)
            k = by_inst[id(dpor)]
            _dpor_restore_state(dpor, posts[k])
            self.last_interleavings = dpor.interleavings
            found = founds[k]
            if found is None:
                return None
            return self._lift(ext, found, violation_fingerprint)

        return [(lambda i=i: resolver(i)) for i in range(len(probes))]


def max_distance_union(a: Optional[int], b: Optional[int]) -> Optional[int]:
    """The looser of two edit-distance budgets (None = unbounded)."""
    if a is None or b is None:
        return None
    return max(a, b)


def _resolve_double_buffer(explicit: Optional[bool] = None) -> bool:
    """Resolve the in-flight-round switch: an explicit constructor arg
    wins (bench and the calibrated tune axis pass one); otherwise the
    feature rides the ``DEMI_ASYNC_MIN`` umbrella flag and defaults on
    only where speculation is free — platforms where host and device are
    disjoint. On CPU the device lanes run on the host's own cores, so a
    mispredicted in-flight launch burns real compute; there the tuner
    (``tune.calibrate_dpor_inflight``) must measure the trade."""
    if explicit is not None:
        return bool(explicit)
    from ..minimization.pipeline import async_min_enabled

    if not async_min_enabled(None):
        return False
    return jax.devices()[0].platform != "cpu"


def _dpor_search_state(dpor: "DeviceDPOR") -> tuple:
    """Snapshot of a DeviceDPOR's host-side search state — everything a
    round mutates. ``test_window`` uses it to run speculative probes'
    rounds eagerly (their device work shares the window launch) while
    committing their instance state only on consult, so an unconsulted
    probe leaves its resumable frontier exactly as the sequential path
    would have."""
    tuner = None
    if dpor.tuner is not None:
        tuner = (
            dpor.tuner.rounds, dpor.tuner.round_batch,
            dpor.tuner.max_distance,
        )
    sleep_state = None
    if dpor.sleep is not None:
        sleep_state = (
            set(dpor.sleep.classes),
            {k: list(v) for k, v in dpor.sleep._node_flips.items()},
            dict(dpor.sleep.pruned_total),
        )
    return (
        set(dpor.explored), list(dpor.frontier), dpor.original,
        dpor.max_distance, dpor.interleavings, dpor.round_batch,
        dict(dpor.async_stats), tuner, set(dpor._explored_digests),
        dpor.host_seconds, dpor.device_seconds,
        dict(dpor._sleep_rows), set(dpor._suppressed),
        set(dpor._suppressed_digests), set(dpor.violation_codes),
        sleep_state, dict(dpor._guides), list(dpor._explored_log),
    )


def _dpor_restore_state(dpor: "DeviceDPOR", state: tuple) -> None:
    (
        dpor.explored, dpor.frontier, dpor.original, dpor.max_distance,
        dpor.interleavings, dpor.round_batch, async_stats, tuner,
        dpor._explored_digests, dpor.host_seconds, dpor.device_seconds,
    ) = (
        set(state[0]), list(state[1]), state[2], state[3], state[4],
        state[5], dict(state[6]), state[7], set(state[8]),
        state[9], state[10],
    )
    dpor._sleep_rows = dict(state[11])
    dpor._suppressed = set(state[12])
    dpor._suppressed_digests = set(state[13])
    dpor.violation_codes = set(state[14])
    if getattr(dpor, "_sharder", None) is not None:
        # Snapshots hold the digest sets FLAT; a sharded instance
        # re-partitions them by digest range on restore (also how an
        # N-shard checkpoint restores into M shards).
        from ..fleet.shard import DigestShards

        dpor._explored_digests = DigestShards(
            dpor._host_shards, dpor._explored_digests
        )
        dpor._suppressed_digests = DigestShards(
            dpor._host_shards, dpor._suppressed_digests
        )
    dpor._guides = dict(state[16])
    # The explored log rolls back with the set; the durable-checkpoint
    # pack cache re-validates itself against it (prefix + last-entry
    # check) and rebuilds when the rollback invalidated it.
    dpor._explored_log = list(state[17])
    if state[15] is not None and dpor.sleep is not None:
        dpor.sleep.classes = set(state[15][0])
        dpor.sleep._node_flips = {
            k: list(v) for k, v in state[15][1].items()
        }
        dpor.sleep.pruned_total = dict(state[15][2])
    if tuner is not None and dpor.tuner is not None:
        (
            dpor.tuner.rounds, dpor.tuner.round_batch,
            dpor.tuner.max_distance,
        ) = tuner


def steering_prescription(
    app: DSLApp,
    cfg: DeviceConfig,
    trace,
    externals: Sequence[ExternalEvent],
) -> Tuple[Tuple[int, ...], ...]:
    """Lower a recorded violating EventTrace to a DPOR prescription (its
    delivery/timer records in order) so the first device execution replays
    the recorded schedule — the device analog of the host scheduler's
    initial-trace steering (DPORwHeuristics.scala:542-555). Prescription
    following is divergence-tolerant, so a projected subsequence's missing
    records are skipped."""
    from .encoding import lower_expected_trace

    projected = (
        trace.filter_failure_detector_messages()
        .filter_checkpoint_messages()
        .subsequence_intersection(list(externals))
    )
    recs = lower_expected_trace(app, cfg, projected, externals, cfg.max_steps)
    keep = recs[np.isin(recs[:, 0], (REC_DELIVERY, REC_TIMER))]
    return tuple(map(tuple, keep.tolist()))


class DeviceDPOR:
    """Frontier-batched DPOR driver: rounds of B prescriptions per kernel
    launch, deepest-first priority, explored-set dedup.

    The frontier persists across ``explore`` calls (resumability — the
    device analog of DPORwHeuristics keeping depGraph/backTrack intact
    across test() calls, :225-254); ``seed`` plants an initial-trace
    prescription; ``max_distance`` caps accepted backtracks by modified
    edit distance to the seeded schedule (ArvindDistanceOrdering's metric
    over record identities).

    ``double_buffer`` (default: on under ``DEMI_ASYNC_MIN`` on non-CPU
    platforms — see ``_resolve_double_buffer``) overlaps rounds: round
    N+1's prescriptions are planned, grouped, and dispatched as a FULL
    in-flight launch while round N's codes are still on device, on the
    prediction that round N's harvest adds nothing that outranks the
    current frontier. A correct prediction makes the next harvest free of
    dispatch latency; a misprediction discards the in-flight launch
    unharvested, so the explored set, frontier, and every per-lane result
    stay bit-identical to the synchronous loop (lane keys depend only on
    the round index, which speculation preserves)."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        program: Sequence[ExternalEvent],
        batch_size: int = 64,
        impl: Optional[str] = None,
        mesh=None,
        prefix_fork: Optional[bool] = None,
        fork_bucket: int = 8,
        fork_min_group: Optional[int] = None,
        double_buffer: Optional[bool] = None,
        kernel=None,
        fork_kernel=None,
        host_path: Optional[str] = None,
        static_independence=None,
        sleep_sets=None,
        key_mode: Optional[str] = None,
        host_shards: Optional[int] = None,
    ):
        assert cfg.record_trace and cfg.record_parents
        self.app = app
        self.cfg = cfg
        # Static may-commute relation resolved FIRST: the sleep-set
        # machinery reuses it as its dependence oracle when both are on.
        self.static_independence = _resolve_static_independence(
            app, static_independence
        )
        # Sleep sets + race-reversal class dedup (analysis/sleep.py; off
        # by default / DEMI_SLEEP_SETS=1): frontier prescriptions carry
        # bounded sleep rows the device kernel tracks wake ordinals for,
        # the racing scan refuses reversals asleep at their branch, and
        # admitted prescriptions dedup on Mazurkiewicz-canonical class
        # keys — counted in analysis.sleep_pruned, never admitted.
        self.sleep = _resolve_sleep_sets(
            app, sleep_sets, self.static_independence
        )
        # Per-lane rng keys: 'position' (the default — key = cumulative
        # batch position) or 'content' (key derived from the
        # prescription's content digest, so a prescription explores the
        # SAME suffix regardless of where pruning shifts it in the
        # round order). Sleep mode defaults to content keys: the A/B
        # contract (pruned explored ⊆ unpruned, violations preserved)
        # only holds when pruning cannot reshuffle every surviving
        # lane's randomness. Padding lanes all share the empty
        # prescription's key under content mode — determinism traded
        # for pad diversification, exactly the redundancy-measurement
        # trade.
        if key_mode is None:
            key_mode = "content" if self.sleep is not None else "position"
        if key_mode not in ("position", "content"):
            raise ValueError(
                f"key_mode must be 'position' or 'content', got {key_mode!r}"
            )
        self.key_mode = key_mode
        impl = impl or os.environ.get("DEMI_DEVICE_IMPL", "xla")
        if self.sleep is not None and impl == "pallas" and mesh is None:
            raise ValueError(
                "sleep sets run on the XLA DPOR kernels (the pallas twin "
                "does not carry the sleep inputs yet)"
            )
        if mesh is not None:
            # Frontier rounds sharded over the device mesh (SURVEY.md
            # §2.8: the batch axis covers EVERY batched workload, the
            # search kernels included). Rounds are padded to batch_size,
            # which must divide over the mesh axis.
            from ..parallel.mesh import LANES, shard_dpor_kernel

            if impl == "pallas":
                import sys

                print(
                    "DeviceDPOR: mesh sharding uses the XLA DPOR kernel; "
                    "ignoring impl=pallas",
                    file=sys.stderr,
                )

            if batch_size % mesh.shape[LANES]:
                raise ValueError(
                    f"batch_size {batch_size} must be a multiple of the "
                    f"mesh axis {mesh.shape[LANES]}"
                )
            if self.sleep is not None:
                # Intra-slice fleet ring: the sleep-set twin shards its
                # extra per-lane inputs (sleep rows, node ordinals) with
                # the batch (parallel/mesh.py).
                from ..parallel.mesh import shard_dpor_sleep_kernel

                self.kernel = shard_dpor_sleep_kernel(
                    app, cfg, mesh, self.sleep.cap,
                    commute_matrix=self.sleep.matrix,
                )
            else:
                self.kernel = shard_dpor_kernel(app, cfg, mesh)
        elif impl == "pallas":
            from .pallas_explore import make_dpor_kernel_pallas

            self.kernel = make_dpor_kernel_pallas(
                app, cfg, block_lanes=min(64, batch_size)
            )
        elif kernel is not None:
            # A caller-shared kernel (DeviceDPOROracle keeps one per
            # app/cfg): every fresh DeviceDPOR otherwise jits its own
            # closure, so a DDMin run probing many subsequences would
            # recompile the identical kernel per subsequence. With sleep
            # sets on the caller must share a SLEEP kernel (same
            # sleep_cap/matrix) — the oracle does.
            self.kernel = kernel
        elif self.sleep is not None:
            self.kernel = make_dpor_kernel(
                app, cfg, sleep_cap=self.sleep.cap,
                commute_matrix=self.sleep.matrix,
            )
        else:
            self.kernel = make_dpor_kernel(app, cfg)
        self.prog = lower_program(app, cfg, list(program))
        self.batch_size = batch_size
        # Prefix-fork (device/fork.py, DEMI_PREFIX_FORK=1 / --prefix-fork):
        # frontier prescriptions grouped by shared prefix; each group
        # resumes from a (LRU-cached) trunk snapshot instead of replaying
        # the prefix per lane. Per-lane keys are assigned by batch
        # position on both paths, so round results are bit-identical.
        from .fork import prefix_fork_enabled

        self._forker = None
        if prefix_fork_enabled(prefix_fork):
            from .fork import (
                PrefixForker,
                make_dpor_prefix_resume_runner,
                make_dpor_prefix_runner,
            )

            if impl == "pallas" and mesh is None:
                import sys

                print(
                    "DeviceDPOR: prefix-fork trunk/fork lanes run on the "
                    "XLA DPOR kernel (bit-identical semantics)",
                    file=sys.stderr,
                )
            if mesh is None:
                self._fork_kernel = fork_kernel or make_dpor_kernel(
                    app, cfg, start_state=True,
                    sleep_cap=self.sleep.cap if self.sleep else 0,
                    commute_matrix=self.sleep.matrix if self.sleep else None,
                )
            elif self.sleep is not None:
                from ..parallel.mesh import shard_dpor_sleep_kernel

                self._fork_kernel = shard_dpor_sleep_kernel(
                    app, cfg, mesh, self.sleep.cap,
                    commute_matrix=self.sleep.matrix, start_state=True,
                )
            else:
                from ..parallel.mesh import shard_dpor_kernel

                self._fork_kernel = shard_dpor_kernel(
                    app, cfg, mesh, start_state=True
                )
            if fork_min_group is None:
                # A trunk run is a SINGLE-lane O(prefix) execution and a
                # fork group is an extra kernel launch: on CPU — where a
                # vectorized lane costs nearly as much as a scalar one
                # and launches are not free — even the 4-7-lane sibling
                # groups the bucketed selection now produces lose to one
                # whole-batch launch when the trunk cache misses (round
                # prefixes are round-unique, so misses dominate; measured
                # on bench config 8). Require half a batch before a CPU
                # trunk pays; on accelerators the batched lanes are
                # effectively free next to the trunk launch, so keep the
                # planner's permissive default.
                fork_min_group = (
                    max(8, batch_size // 2)
                    if jax.devices()[0].platform == "cpu"
                    else 2
                )
            self._forker = PrefixForker(
                make_dpor_prefix_runner(app, cfg),
                bucket=fork_bucket,
                min_group=fork_min_group,
                driver="dpor",
                # Prescribed-resume trunks: a trunk-cache miss resumes
                # the nearest cached ancestor over the remaining
                # prescription rows (O(bucket)) instead of re-following
                # the full prefix (O(p)) — the DPOR twin of the replay
                # checker's hierarchical trunks.
                resume_runner=make_dpor_prefix_resume_runner(app, cfg),
                # Cross-round trunk reuse (the PR 6 ~0%-hit debt):
                # DEMI_FORK_ANCHOR_STRIDE=N caches anchor snapshots
                # every N buckets while building a trunk, so a later
                # round's round-unique prefix resumes the deepest
                # shared anchor instead of starting over. Keys are
                # match-normalized (see _dispatch_round), which is what
                # makes cross-round sharing possible at all. Measured
                # on the config-8 sequential frontier: trunk hit rate
                # 0.13 -> 0.64 by round 6 (parent + anchor resumes);
                # under the double-buffered round composition the
                # anchors cost extra launches without hits on CPU —
                # so, like every fork feature, opt-in until measured
                # where launches are cheap.
                anchor_stride=int(
                    os.environ.get("DEMI_FORK_ANCHOR_STRIDE", "0")
                ) or None,
                # Anchors live or die by LRU headroom: a chain caches
                # one snapshot per stride boundary, and the SHALLOW
                # boundaries — the ones every racing family shares —
                # are also the least-recently-used entries, so a tight
                # cache evicts exactly the reusable ones first. One
                # snapshot is a single lane's state (tens of KB), so
                # hundreds stay cheap.
                capacity=(
                    512
                    if os.environ.get("DEMI_FORK_ANCHOR_STRIDE", "0") != "0"
                    else 32
                ),
            )
        self._mesh = mesh
        self._double_buffer = _resolve_double_buffer(double_buffer)
        # In-flight round economics (the signal calibrate_dpor_inflight
        # and bench config 8 read): speculative launches, and how many
        # were used vs discarded.
        self.async_stats = {
            "inflight_rounds": 0,
            "inflight_hits": 0,
            "inflight_waste": 0,
        }
        # Frontier host path: 'vectorized' (batch-native racing analysis,
        # digest-keyed dedup) or 'legacy' (per-lane scan + per-pair tuple
        # loop). Both produce bit-identical explored/frontier/results —
        # pinned by tests/test_host_path.py and bench config 8.
        self.host_path = _resolve_host_path(host_path)
        # Host-share accounting (always on — two perf_counter reads per
        # round): wall time blocked harvesting device results vs
        # everything else in the frontier loop. The dpor.host_share gauge
        # (obs) and bench configs 2/8 read these.
        self.host_seconds = 0.0
        self.device_seconds = 0.0
        self.explored: Set[Tuple] = set()
        self.frontier: List[Tuple] = [tuple()]
        self.explored.add(tuple())
        # Admission-ordered log of the explored set (kept in lockstep
        # with ``explored`` — __init__/seed/_admit are the only
        # writers). The durable-checkpoint codec serializes the log as
        # one packed int32 blob and the frontier as INDICES into it, and
        # keeps an incremental pack cache so each snapshot packs only
        # the entries admitted since the last one (demi_tpu/persist).
        self._explored_log: List[Tuple] = [tuple()]
        self._persist_pack_cache = None
        # Digest twin of the explored set (16-byte content keys over the
        # packed prescription rows): the vectorized path's membership
        # check, maintained in lockstep with ``explored`` so a redundant
        # prescription never has to materialize a Python tuple.
        from ..native import prescription_digest

        self._explored_digests: Set[bytes] = {prescription_digest(tuple())}
        # Adaptive (n_presc, n_rows) buffer hint for the batch scan.
        self._batch_size_hint: Optional[Tuple[int, int]] = None
        # Persistent scan output buffers for the unsharded batch path:
        # the adaptive size hint lives per INSTANCE (native.ScanBuffers)
        # instead of per call, so a steady-state round reallocates
        # nothing.
        from ..native import ScanBuffers

        self._scan_buffers = ScanBuffers()
        # Digest-range-sharded admission (fleet/shard.py; host_shards >
        # 1 via the constructor, --host-shards, or DEMI_HOST_SHARDS):
        # the round's scan/filter/dedup pipeline runs as N concurrent
        # digest-range shards, then a serial canonical merge
        # (_admit_stream) applies fresh admissions in the sequential
        # round order — explored/class/violation sets, frontier, and
        # first-found record stay bit-identical at any shard count.
        # The digest sets become DigestShards (a drop-in set facade
        # partitioned by range) so each shard's dedup thread owns a
        # disjoint slice. Composes with sleep sets, static pruning,
        # prefix-fork, and double-buffering: sharding only touches how
        # one harvested round's candidates are scanned and deduped.
        self._host_shards = _resolve_host_shards(host_shards)
        self._sharder = None
        if self._host_shards > 1:
            from ..fleet.shard import DigestShards, ShardedAdmission

            self._sharder = ShardedAdmission(self._host_shards)
            self._explored_digests = DigestShards(
                self._host_shards, self._explored_digests
            )
        self.original: Optional[Tuple] = None
        self.max_distance: Optional[int] = None
        # Closed seeded exploration (analysis/delta.py): when False, the
        # prescription-free PADDING lanes still run (the kernel batch
        # shape is compiled) but their harvested races are not admitted
        # to the frontier — every explored class then descends from a
        # seeded prescription and carries an exact trunk-divergence
        # index in its meta, which is what differential re-verification
        # transfers on. Default True keeps the classic behavior: pads
        # diversify the frontier with random exploration.
        self.pad_exploration: bool = True
        self.interleavings = 0
        # Sleep-set side state: per-prescription sleep rows (frontier
        # entries stay plain tuples — selection, dedup, and every parity
        # surface are untouched), plus the class-suppressed sets kept in
        # the same tuple/digest lockstep as explored/_explored_digests.
        self._sleep_rows: Dict[Tuple, Tuple[Tuple[int, ...], ...]] = {}
        self._suppressed: Set[Tuple] = set()
        self._suppressed_digests: Set[bytes] = set()
        if self._sharder is not None:
            from ..fleet.shard import DigestShards

            self._suppressed_digests = DigestShards(self._host_shards)
        # Wakeup-sequence guides (sleep mode only): a reversal's
        # EXECUTION follows the full bounded wakeup sequence — prefix,
        # flipped record, then the source lane's remaining deliveries in
        # order (divergence-tolerant) — while its frontier IDENTITY
        # stays ``prefix + flip`` (wakeup-tree node identity: suffix
        # reorderings collapse into the same node, which is what turns
        # classic DPOR's re-derivations into raw-redundant hits). Keyed
        # by the identity tuple; ``_pack`` substitutes the guide rows.
        self._guides: Dict[Tuple, np.ndarray] = {}
        # Admitted prescription -> canonical class key (sleep mode):
        # lives exactly as long as the guide (popped once executed), so
        # per-round violation witnesses and the published ledger's
        # pending set can attribute lanes to classes.
        self._class_of: Dict[Tuple, tuple] = {}
        if self.sleep is not None:
            self.sleep.note_class(())  # the root schedule's class
            self._class_of[()] = ()
        # Distinct violation codes observed across all lanes of all
        # rounds (always tracked — one np.unique per round): the
        # violation-set preservation surface the sleep-set A/B asserts.
        self.violation_codes: Set[int] = set()
        # Per-code canonical first-found witness: the violating lane
        # record with the smallest trace digest seen so far —
        # {"sha", "class", "trace"}. Min-digest (not chronology) makes
        # the record order-free, so a differential re-exploration and a
        # scratch run converge on identical witnesses (analysis/delta).
        self.violation_witnesses: Dict[int, Dict[str, object]] = {}
        # Continuous observability (obs/journal.py): rounds executed so
        # far (1-based after the first round; checkpointed + restored so
        # a resumed journal stays generation-contiguous) and the last
        # round's local stats, stashed by _process_round for the journal
        # record — a tiny always-on dict, measured inside bench config
        # 11's <1% budget.
        self.round_index = 0
        self._last_round: Dict[str, object] = {}
        # Measurement-guided budget control (demi_tpu/tune): when set, the
        # tuner sees each round's fresh/redundant/pruned prescription
        # counts and adjusts max_distance and round_batch online. The
        # kernel batch stays compiled at batch_size; round_batch caps how
        # many FRONTIER prescriptions are dispatched per round — surplus
        # lanes run prescription-free random exploration, so a
        # redundant-saturated frontier trades prescribed lanes for
        # diversification instead of re-deriving known schedules.
        self.tuner = None
        self.round_batch = batch_size

    def seed(self, prescription: Tuple[Tuple[int, ...], ...]) -> None:
        """Plant an initial prescription at the head of the frontier (and
        fix it as the edit-distance origin)."""
        from ..native import prescription_digest

        self.original = prescription
        if prescription not in self.explored:
            self.explored.add(prescription)
            self._explored_log.append(prescription)
            self._explored_digests.add(prescription_digest(prescription))
            self.frontier.insert(0, prescription)
            if self.sleep is not None and prescription:
                # Seeded rows carry no source-lane positions: creation
                # edges onto them never fire (class splits, never
                # falsely merges — see canonical_class_key). The seed's
                # guide is the prescription itself.
                ckey = self.sleep.class_key(
                    np.asarray(prescription, np.int32), None,
                    self.cfg.rec_width,
                )
                # TRUNK_BIT: the seed IS the trunk (zero reversals) —
                # differential exploration always re-executes it (trunk
                # revalidation, analysis/delta.py), and its descendants
                # start their reversal chains from an empty mask.
                from ..analysis.sleep import TRUNK_BIT

                self.sleep.note_class(
                    ckey, guide=prescription, plen=len(prescription),
                    dmask=TRUNK_BIT,
                )
                self._class_of[prescription] = ckey

    def checkpoint_state(self) -> dict:
        """JSON-able snapshot of everything a round mutates (frontier,
        explored tuple/digest sets, sleep rows + class ledger, guides,
        violation codes, rng round counters) — the durable twin of the
        in-memory ``_dpor_search_state``. Round-trips bit-identically:
        a fresh DeviceDPOR built with the same constructor arguments and
        ``restore_state(payload)`` continues exactly where this one
        stood (tests/test_persist.py)."""
        from ..persist.checkpoint import device_dpor_payload

        return device_dpor_payload(self)

    def restore_state(self, payload: dict) -> None:
        """Inverse of ``checkpoint_state``; raises
        ``persist.CheckpointMismatch`` when the payload was captured
        under a different workload shape."""
        from ..persist.checkpoint import restore_device_dpor

        restore_device_dpor(self, payload)

    def _supervised_harvest(
        self, parts, batch: List[Tuple], prescs: np.ndarray, keys
    ):
        """Harvest one round under the launch supervisor: a failed or
        poisoned launch re-executes the round from its (pure) inputs —
        the round is a function of (prescs, keys, batch) alone, so a
        retry is bit-identical and nothing in the search state needs
        rewinding. Exhausted retries re-raise (strict-io makes that a
        StrictIOError); there is no host twin for the DPOR kernel."""
        from ..persist.supervisor import SUPERVISOR

        def attempt(n: int):
            p = parts if n == 0 else self._dispatch_round(
                prescs, keys, batch
            )
            return self._harvest_round(p, len(batch))

        return SUPERVISOR.run(attempt, label="dpor.launch")

    def _pack(self, prescriptions: List[Tuple]) -> np.ndarray:
        r, w = self.cfg.max_steps, self.cfg.rec_width
        out = np.zeros((len(prescriptions), r, w), np.int32)
        for k, presc in enumerate(prescriptions):
            guide = (
                self._guides.get(presc) if self.sleep is not None else None
            )
            if guide is not None:
                m = min(len(guide), r)
                out[k, :m] = guide[:m]
            elif presc:
                m = min(len(presc), r)
                out[k, :m] = np.asarray(presc[:m], np.int32)
        return out

    def _sleep_from(self, batch: List[Tuple]) -> np.ndarray:
        """Per-lane node ordinal (sleep mode): the delivery count of the
        lane's IDENTITY prescription (prefix + flip) — wake tracking and
        sleep-membership checks apply at/after it. Guide rows beyond the
        identity are ordinary prescribed deliveries and ARE tracked."""
        return np.asarray([len(p) for p in batch], np.int32)

    def _progs(self, b: int) -> ExtProgram:
        from .explore import broadcast_program

        return broadcast_program(self.prog, b)

    def _select_batch(
        self, frontier: List[Tuple]
    ) -> Tuple[List[Tuple], List[Tuple]]:
        """Pure round selection: ``(batch, rest)`` for one frontier round
        — deepest-first with a seeded initial prescription pinned to the
        head, padded to ``batch_size`` with prescription-free lanes. Does
        NOT mutate the input, and is deterministic in (frontier,
        round_batch): because rounds select from the FROZEN generation
        (fresh prescriptions join the next generation — see ``explore``),
        the double-buffered loop's in-flight round is the real next round
        whenever this selection re-runs unchanged after the harvest.

        Depth orders at BUCKET granularity (8 rows — the planner's
        default trunk bucket) with lexicographic content order within a
        bucket (fork-group growth): prescriptions sharing long prefixes
        — same-lane racing families, equal-depth siblings from ANY
        generation — cluster on the same side of the round cut instead
        of scattering across rounds by exact depth. Measured on the
        config-8 frontier this turns the structural 2-lane sibling
        groups into 4-7-lane groups (the size a resume trunk pays for on
        CPU) while staying within 7 rows of strict deepest-first. The
        constant bucket keeps selection independent of any fork
        configuration, so every host-path/async variant explores the
        identical schedule space."""
        frontier = self._ordered_frontier(frontier)
        take = max(1, min(self.round_batch, self.batch_size))
        batch, rest = frontier[:take], frontier[take:]
        batch = batch + [tuple()] * (self.batch_size - len(batch))
        return batch, rest

    def _ordered_frontier(self, frontier: List[Tuple]) -> List[Tuple]:
        """The ONE round-order rule (see ``_select_batch``): a seeded
        original pinned at the head, then deepest-bucket-first with
        lexicographic content order within a bucket. Bench config 8's
        sibling-clustering measurement calls this too, so it can never
        measure an ordering the frontier doesn't actually use."""
        frontier = list(frontier)
        head, rest = (
            ([frontier[0]], frontier[1:])
            if self.original is not None and frontier
            and frontier[0] == self.original
            else ([], frontier)
        )
        rest.sort(key=lambda p: (-(len(p) // 8), p))
        return head + rest

    def _merge_generations(
        self, gen: List[Tuple], pending: List[Tuple]
    ) -> Tuple[List[Tuple], List[Tuple]]:
        """Cross-generation round filling (fork-group growth): when the
        frozen generation can no longer FILL a round, the next generation
        joins it — so a round's batch carries equal-depth prescriptions
        from both generations instead of padding with prescription-free
        lanes, and the PrefixPlanner gets sibling groups worth a resume
        trunk. Deterministic in (gen, pending, round_batch): both the
        synchronous loop and the double-buffered speculation check derive
        the same decision, so a merge at a generation boundary costs at
        most one discarded in-flight launch, never a divergence."""
        if not pending:
            return gen, pending
        take = max(1, min(self.round_batch, self.batch_size))
        if len(gen) >= take:
            return gen, pending
        return gen + pending, []

    def _round_keys(self, n: int, base: int, batch: Optional[List[Tuple]] = None):
        """Per-lane keys for one round. ``key_mode='position'`` (the
        default): position in the cumulative interleaving count — every
        round is padded to ``batch_size``, so ``base`` advances
        deterministically and a speculative round N+1 dispatched before
        round N's harvest derives the exact keys the synchronous loop
        would. ``key_mode='content'`` (sleep-set mode): each lane's key
        derives from its prescription's content digest, so a
        prescription explores the identical suffix no matter where
        pruning shifts it in the round order — the property the sleep
        A/B's explored-subset/violation-preservation contract rests on."""
        if self.key_mode == "content" and batch is not None:
            from ..native import prescription_digest

            seeds = np.asarray(
                [
                    int.from_bytes(prescription_digest(p)[:4], "little")
                    for p in batch
                ],
                np.uint32,
            )
            return jax.vmap(
                lambda s: jax.random.fold_in(jax.random.PRNGKey(0), s)
            )(seeds)
        return jax.vmap(
            lambda s: jax.random.fold_in(jax.random.PRNGKey(0), s)
        )(np.arange(base, base + n, dtype=np.uint32))

    def _dispatch_round(self, prescs: np.ndarray, keys, batch: List[Tuple]):
        """Launch one frontier round's lane work WITHOUT pulling results
        — the dispatch half of the round (pair with ``_harvest_round``).
        Returns a list of ``(indices, device LaneResult)`` parts;
        ``indices=None`` means the whole batch in order.

        Scratch mode: one whole-batch kernel launch. Prefix-fork mode:
        prescriptions grouped by bucketed shared prefix (PrefixPlanner);
        each group resumes from a cached trunk snapshot via the
        ``start_state=`` kernel — a trunk-cache miss first tries to
        derive the trunk by resuming the nearest cached ancestor over
        the remaining prescribed rows (``trunk_hier_prescribed``,
        O(bucket) instead of O(prefix)) — and everything else
        (prescription-free pads included) runs the scratch kernel.
        Per-lane keys follow batch position on both paths, so per-lane
        results are bit-identical."""
        from ..obs.profiler import PROFILER

        sleeps = self._pack_sleep(batch) if self.sleep is not None else None
        sfrom = self._sleep_from(batch) if sleeps is not None else None
        if self._forker is None or len(batch) < 2:
            t0 = time.perf_counter() if PROFILER.enabled else 0.0
            if sleeps is None:
                out = [
                    (None, self.kernel(self._progs(len(batch)), prescs, keys))
                ]
            else:
                out = [(
                    None,
                    self.kernel(
                        self._progs(len(batch)), prescs, keys, sleeps, sfrom
                    ),
                )]
            if PROFILER.enabled:
                PROFILER.dispatch(
                    "dpor", len(batch), time.perf_counter() - t0
                )
            return out
        from .fork import padded_size, prefix_digest

        keys = np.asarray(keys)
        lengths = np.asarray(
            [len(self._guides.get(p, p)) for p in batch]
            if self.sleep is not None
            else [len(p) for p in batch]
        )
        # Plan and key trunks over MATCH-NORMALIZED rows: the
        # prescribed-dispatch matcher never reads the parent/prev
        # bookkeeping columns, so two prescriptions identical in the
        # matchable columns execute to bit-identical trunk states even
        # when their source lanes recorded different trace positions.
        # Keying on raw bytes was why cross-round reuse measured ~0%
        # (a re-derived prefix differs from its ancestor only in the
        # flip row's prev column); normalized keys let round N+1's
        # trunks resume round N's. Lanes still receive the ORIGINAL
        # rows — only grouping/caching identity changes.
        plan_rows = prescs.copy()
        plan_rows[:, :, self.cfg.rec_width - 2:] = 0
        groups, scratch = self._forker.plan(plan_rows, lengths)
        if sleeps is not None:
            # Sleep mode: trunk prefixes stop BELOW every member's node
            # ordinal, so the shared (untracked) trunk segment never
            # enters the region the per-lane wake tracking must cover.
            bucket = self._forker.planner.bucket
            adjusted = []
            for g in groups:
                cap = (min(int(sfrom[i]) for i in g.indices) // bucket) * bucket
                if cap <= 0:
                    scratch.extend(g.indices)
                    continue
                if g.prefix_len > cap:
                    g = g._replace(
                        prefix_len=cap,
                        key=prefix_digest(
                            plan_rows[g.indices[0], :cap].tobytes()
                        ),
                    )
                adjusted.append(g)
            groups = adjusted
        parts: List[Tuple[Optional[List[int]], LaneResult]] = []

        for g in groups:
            if not self._forker.should_fork(g):
                scratch.extend(g.indices)
                continue
            # Trunk follows the normalized rows (execution-identical —
            # the matcher ignores the zeroed columns — and the key space
            # the ancestor walk + anchors live in).
            trunk_presc = np.zeros_like(plan_rows[0])
            trunk_presc[: g.prefix_len] = plan_rows[
                g.indices[0], : g.prefix_len
            ]
            t0 = time.perf_counter() if PROFILER.enabled else 0.0
            snap, trunk_steps, hit = self._forker.trunk_hier_prescribed(
                g.key,
                ExtProgram(*(np.asarray(x) for x in self.prog)),
                trunk_presc,
                jax.random.PRNGKey(0),
                g.prefix_len,
            )
            if PROFILER.enabled:
                PROFILER.trunk(
                    "dpor-trunk", 1, time.perf_counter() - t0,
                    shape=f"p={g.prefix_len}",
                )
            full = g.indices + [g.indices[0]] * (
                padded_size(len(g.indices), self._mesh) - len(g.indices)
            )
            t0 = time.perf_counter() if PROFILER.enabled else 0.0
            if sleeps is None:
                res_g = self._fork_kernel(
                    self._progs(len(full)), prescs[full], keys[full], snap
                )
            else:
                res_g = self._fork_kernel(
                    self._progs(len(full)), prescs[full], keys[full],
                    sleeps[full], sfrom[full], snap,
                )
            if PROFILER.enabled:
                PROFILER.dispatch(
                    "dpor-fork", len(full), time.perf_counter() - t0
                )
            parts.append((g.indices, res_g))
            self._forker.note_group(len(g.indices), trunk_steps, hit)
            obs.histogram("dpor.prefix_group_size").observe(len(g.indices))
        if scratch:
            full = scratch + [scratch[0]] * (
                padded_size(len(scratch), self._mesh) - len(scratch)
            )
            t0 = time.perf_counter() if PROFILER.enabled else 0.0
            if sleeps is None:
                res_s = self.kernel(
                    self._progs(len(full)), prescs[full], keys[full]
                )
            else:
                res_s = self.kernel(
                    self._progs(len(full)), prescs[full], keys[full],
                    sleeps[full], sfrom[full],
                )
            if PROFILER.enabled:
                PROFILER.dispatch(
                    "dpor", len(full), time.perf_counter() - t0
                )
            parts.append((scratch, res_s))
            self._forker.note_scratch(len(scratch))
        return parts

    def _pack_sleep(self, batch: List[Tuple]) -> np.ndarray:
        """Fixed-shape sleep input for one round: each lane's sleep rows
        ([B, sleep_cap, recw] int32, kind 0 = empty slot) looked up from
        the frontier side-table — prescription-free padding lanes carry
        none."""
        S, w = self.sleep.cap, self.cfg.rec_width
        out = np.zeros((len(batch), S, w), np.int32)
        for k, presc in enumerate(batch):
            rows = self._sleep_rows.get(presc)
            if rows:
                for s, row in enumerate(rows[:S]):
                    out[k, s, : len(row)] = row
        return out

    def _harvest_round(self, parts, batch_len: int) -> LaneResult:
        """Block on a dispatched round's parts and merge them back into
        batch order (np arrays quack like the LaneResult — or
        DporSleepResult — the harvesting loops read)."""
        from ..obs.profiler import PROFILER

        t0 = time.perf_counter() if PROFILER.enabled else 0.0
        if len(parts) == 1 and parts[0][0] is None:
            res = parts[0][1]
            jax.block_until_ready(res.violation)
            if PROFILER.enabled:
                PROFILER.block(
                    "dpor", batch_len, time.perf_counter() - t0
                )
            return res
        res_type = type(parts[0][1])
        merged = {}
        for field in res_type._fields:
            ref = np.asarray(getattr(parts[0][1], field))
            merged[field] = np.zeros((batch_len,) + ref.shape[1:], ref.dtype)
        for idx, res in parts:
            jax.block_until_ready(res.violation)
            for field in res_type._fields:
                merged[field][np.asarray(idx)] = np.asarray(
                    getattr(res, field)
                )[: len(idx)]
        if PROFILER.enabled:
            PROFILER.block("dpor", batch_len, time.perf_counter() - t0)
        return res_type(**merged)

    def _process_round(
        self,
        res: LaneResult,
        batch: List[Tuple],
        target_code: Optional[int],
        frontier: List[Tuple],
        frontier_extra: int = 0,
    ) -> Optional[Tuple[np.ndarray, int]]:
        """The host half of a frontier round: telemetry, the violation
        scan, racing-prescription derivation (appended to ``frontier`` in
        place — the caller's NEXT-generation list under the frozen-
        generation policy), and tuner feedback (``frontier_extra`` counts
        worklist entries outside the sink list — the frozen generation's
        remainder — so the tuner sees the full frontier size). Returns a
        violating lane's (records, trace_len) or None.

        The default ``host_path='vectorized'`` derives the whole round's
        prescriptions in ONE batch-native call (packed int32 rows +
        per-lane offsets — native/trace_analysis.cpp or the NumPy
        fallback), dedups against the explored set on vectorized content
        digests, and only materializes Python tuples for the FRESH
        prescriptions that actually join the frontier. ``'legacy'`` keeps
        the per-lane scan + per-pair tuple loop; outputs are bit-identical
        (tests/test_host_path.py)."""
        self.interleavings += len(batch)
        if obs.enabled():
            # Device-lane totals for the round (one on-device
            # reduction, one pull) + the exploration-efficiency
            # counters optimal-DPOR tuning reads (redundant = already
            # explored, pruned = over the edit-distance cap).
            from ..obs import lane_stats as _ls

            _ls.record(
                _ls.reduce_lanes(
                    res.status, res.violation, res.deliveries,
                    len(batch),
                    invariant_interval=self.cfg.invariant_interval,
                ),
                driver="dpor",
            )
            obs.counter("dpor.interleavings").inc(len(batch))
        violations = np.asarray(res.violation)[: len(batch)]
        traces = np.asarray(res.trace)
        lens = np.asarray(res.trace_len)
        # Violation-set ledger (always on — one np.unique per round):
        # every distinct nonzero code any lane of any round produced,
        # the preservation surface the sleep-set A/B asserts against.
        round_codes = [int(c) for c in np.unique(violations) if c != 0]
        self.violation_codes.update(round_codes)
        if self.sleep is not None and round_codes:
            # Canonical per-code first-found witness: keep the violating
            # lane whose trace digest is smallest. Min-digest (not
            # chronology) is order-free, so a differential re-run that
            # executes the same prescriptions in different rounds
            # converges on the SAME witness as scratch (analysis/delta).
            import hashlib as _hl

            for code in round_codes:
                for b in np.flatnonzero(violations == code):
                    b = int(b)
                    tr = traces[b][: int(lens[b])]
                    sha = _hl.sha256(tr.tobytes()).hexdigest()[:16]
                    cur = self.violation_witnesses.get(code)
                    if cur is not None and str(cur["sha"]) <= sha:
                        continue
                    self.violation_witnesses[code] = {
                        "sha": sha,
                        "class": self._class_of.get(
                            batch[b] if b < len(batch) else ()
                        ),
                        "trace": np.array(tr, copy=True),
                    }
        hit_mask = (
            violations != 0
            if target_code is None
            else (violations != 0) & (violations == target_code)
        )
        hit_lanes = np.flatnonzero(hit_mask)
        hit = (
            (traces[hit_lanes[0]], int(lens[hit_lanes[0]]))
            if len(hit_lanes)
            else None
        )
        # Local fresh/redundant/pruned counts: the tuner's per-round
        # signal, needed whether or not telemetry is on (the obs
        # counters still carry the cross-round totals).
        if self.host_path != "vectorized":
            fresh_n, redundant_n, pruned_n = self._derive_legacy(
                traces, lens, len(batch), frontier, batch=batch, res=res
            )
        elif self._sharder is not None:
            fresh_n, redundant_n, pruned_n = self._derive_sharded(
                traces, lens, len(batch), frontier, batch=batch, res=res
            )
        else:
            fresh_n, redundant_n, pruned_n = self._derive_batch(
                traces, lens, len(batch), frontier, batch=batch, res=res
            )
        # Round-local stats for the journal record (obs/journal.py):
        # stashed always — a handful of ints next to a kernel launch.
        self._last_round = {
            "batch": len(batch),
            "depth": max((len(p) for p in batch), default=0),
            "fresh": int(fresh_n),
            "redundant": int(redundant_n),
            "distance_pruned": int(pruned_n),
            "violations": round_codes,
        }
        if self._sharder is not None:
            # Per-shard scan/dedup stats for the fleet.host_shard
            # journal records + the top FLEET panel's utilization bars.
            self._last_round["host_shards"] = self._sharder.last_stats
        if redundant_n:
            obs.counter("dpor.prescriptions_redundant").inc(redundant_n)
        if pruned_n:
            obs.counter("dpor.prescriptions_distance_pruned").inc(pruned_n)
        obs.gauge("dpor.explored_set_size").set(len(self.explored))
        if self.sleep is not None:
            ratio = self.sleep.redundancy_ratio(len(self.explored))
            if ratio is not None:
                obs.gauge("dpor.redundancy_ratio").set(round(ratio, 4))
        if self.tuner is not None:
            self.tuner.observe_round(
                fresh=fresh_n, redundant=redundant_n, pruned=pruned_n,
                frontier=len(frontier) + frontier_extra,
            )
            self.round_batch = self.tuner.round_batch
            if self.tuner.max_distance is not None:
                self.max_distance = self.tuner.max_distance
        if self.sleep is not None:
            # A harvested prescription never re-enters the worklist
            # (explored-set membership), so its guide and sleep rows are
            # dead — drop them, bounding the side tables to the live
            # frontier instead of the whole explored history. (An
            # unharvested in-flight round that gets requeued was never
            # processed here, so its entries survive for re-dispatch.)
            for p in batch:
                self._guides.pop(p, None)
                self._sleep_rows.pop(p, None)
                # Executed ⇒ no longer pending; witness capture above
                # already consumed the class attribution for this round.
                self._class_of.pop(p, None)
        return hit

    def _admit(
        self, presc: Tuple, key: Optional[bytes], frontier: List[Tuple]
    ) -> bool:
        """Distance-gate + record one non-redundant prescription (shared
        by both host paths). Returns True when the prescription joined
        the frontier. ``key=None`` (the legacy path, which dedups on the
        tuple set alone) skips the digest-set upkeep — the two sets only
        need lockstep within one host path's lifetime."""
        if (
            self.max_distance is not None
            and self.original is not None
            and arvind_distance(presc, self.original) > self.max_distance
        ):
            return False
        self.explored.add(presc)
        self._explored_log.append(presc)
        if key is not None:
            self._explored_digests.add(key)
        frontier.append(presc)
        return True

    def _sleep_class_check(
        self, presc: Tuple, rows, own_pos, flip, branch: int,
        lane_presc: Tuple, wake_row, ckey=None,
    ):
        """The class-dedup half of sleep-set admission for ONE fresh
        candidate (shared by both host paths — parity by construction).
        Returns ``(verdict, commit)``: verdict 'class' means the
        candidate's Mazurkiewicz class was already scheduled (suppress);
        verdict None means admit-eligible, and ``commit()`` — called
        after ``_admit`` accepts — registers the class, assigns the
        child's sleep rows (earlier siblings at the node + the source
        lane's still-asleep rows, filtered by independence with the
        flip), and appends the flip to the node's wakeup ledger."""
        sleep = self.sleep
        recw = self.cfg.rec_width
        if ckey is None:
            ckey = sleep.class_key(rows, own_pos, recw)
        if sleep.prune and sleep.class_seen(ckey):
            sleep.note_pruned(klass=1, tier="device")
            # Warm-start accounting: a hit satisfied by PRIOR-run /
            # other-host coverage (fleet class store) counts separately.
            sleep.note_warm(ckey)
            if sleep.audit:
                sleep.note_pruned_prescription(presc)
            return "class", None

        def commit(guide=None):
            # Reversal-chain tag mask: this child is its parent's class
            # plus ONE race reversal — the flip moved before the row it
            # displaced (``guide[branch + 1]``, when the lane's tail
            # survived divergence tolerance). Its footprint is the
            # parent's chain mask (trunk marker dropped) plus both rows
            # of the reversed pair — recorded here, at admission, when
            # the pair is exact knowledge. Unknown parent lineage
            # (root-descended pads, no recorded mask) stays -1 —
            # differential exploration then falls back to the
            # conservative full-key mask.
            from ..analysis.sleep import TRUNK_BIT, guide_row_tag, tag_bit

            pmeta = sleep.class_meta.get(self._class_of.get(lane_presc))
            pmask = (
                int(pmeta[3])
                if pmeta is not None and len(pmeta) > 3 else -1
            )
            if guide is None or pmask < 0:
                dmask = -1
            else:
                dmask = (pmask & ~TRUNK_BIT) | tag_bit(
                    guide_row_tag(flip)
                )
                if branch + 1 < len(guide):
                    dmask |= tag_bit(guide_row_tag(guide[branch + 1]))
            sleep.note_class(
                ckey, guide=guide, plen=len(presc), dmask=dmask
            )
            self._class_of[presc] = ckey
            node_key = np.ascontiguousarray(
                np.asarray(presc[:-1], np.int32).reshape(len(presc) - 1, -1)
            ).tobytes() if len(presc) > 1 else b""
            inherited: List[Tuple[int, ...]] = []
            if wake_row is not None:
                lane_sleep = self._sleep_rows.get(lane_presc, ())
                presc_deliv = int(wake_row[1])
                if branch >= presc_deliv:
                    for s, srow in enumerate(lane_sleep):
                        if s < len(wake_row[0]) and int(wake_row[0][s]) >= branch:
                            inherited.append(srow)
            child = sleep.child_sleep_rows(node_key, flip, recw, inherited)
            if child:
                self._sleep_rows[presc] = child
            sleep.note_admitted_flip(node_key, flip)

        return None, commit

    def _make_guide(
        self, deliv: List[Tuple[int, ...]], branch: int,
        flip: Tuple[int, ...], flip_ord: Optional[int],
    ) -> np.ndarray:
        """Bounded wakeup sequence for one admitted reversal (sleep
        mode): the source lane's deliveries before the branch, the
        flipped record, then the lane's remaining deliveries in order
        with the flipped one removed — so the reversal's subtree
        revisits the source schedule modulo exactly the reversed race
        (divergence tolerance skips rows the flip invalidated), instead
        of diverging into fresh randomness at the node.

        ``flip_ord=None`` locates the flip by FULL-row equality past
        the branch — exact, not approximate: same-receiver deliveries
        always differ in the ``prev`` column (the per-receiver
        program-order chain is strictly increasing), so a full-row
        match identifies the flipped delivery uniquely. Both host
        paths use this one rule so their guides are bit-identical by
        construction."""
        if flip_ord is None:
            flip_ord = next(
                (
                    t
                    for t in range(branch + 1, len(deliv))
                    if deliv[t] == flip
                ),
                None,
            )
        rows = list(deliv[:branch]) + [flip]
        if flip_ord is not None:
            rows += list(deliv[branch:flip_ord]) + list(deliv[flip_ord + 1:])
        return np.asarray(rows[: self.cfg.max_steps], np.int32)

    def _sleep_ctx(self, batch: List[Tuple], res) -> Optional[tuple]:
        """The racing scan's per-lane sleep inputs for one harvested
        round: the packed sleep rows the kernel consumed (a pure
        function of the batch — identical to what was dispatched) plus
        the device-tracked wake/slept/prescribed-count observations."""
        if self.sleep is None or not hasattr(res, "sleep_wake"):
            return None
        n = len(batch)
        return (
            self._pack_sleep(batch),
            np.asarray(res.sleep_wake)[:n],
            np.asarray(res.sleep_slept)[:n],
            self._sleep_from(batch),
        )

    def _derive_batch(
        self, traces, lens, n_lanes: int, frontier: List[Tuple],
        batch: Optional[List[Tuple]] = None, res=None,
    ) -> Tuple[int, int, int]:
        """Vectorized prescription derivation: one batch-native racing
        call for the whole round, content-digest dedup over the packed
        rows, tuples materialized only for admitted candidates (the
        shared ``_admit_stream`` loop). Returns (fresh, redundant,
        pruned) counts."""
        from ..native import digest_keys, racing_prescriptions_batch
        from ..obs.profiler import PROFILER

        recw = self.cfg.rec_width
        sleep_ctx = (
            self._sleep_ctx(batch, res)
            if batch is not None and res is not None
            else None
        )
        t0 = time.perf_counter() if PROFILER.enabled else 0.0
        rows, offsets, lanes, digests = racing_prescriptions_batch(
            traces[:n_lanes], lens[:n_lanes], recw,
            size_hint=self._batch_size_hint,
            independence=self.static_independence,
            sleep=self.sleep, sleep_ctx=sleep_ctx,
            buffers=self._scan_buffers,
        )
        if PROFILER.enabled:
            PROFILER.host_scan(
                "dpor-host-scan", n_lanes, time.perf_counter() - t0
            )
        # Adaptive buffer sizing: the next round's scan allocates for
        # this round's volume (+ slack) instead of a blind worst case.
        self._batch_size_hint = (
            max(64, (len(digests) * 5) // 4),
            max(256, (len(rows) * 5) // 4),
        )
        keys = digest_keys(digests)
        return self._admit_stream(
            rows, offsets, lanes, keys, traces, lens, batch, sleep_ctx,
            frontier,
        )

    def _derive_sharded(
        self, traces, lens, n_lanes: int, frontier: List[Tuple],
        batch: Optional[List[Tuple]] = None, res=None,
    ) -> Tuple[int, int, int]:
        """Digest-range-sharded derivation (host_shards > 1): the lane
        scan + static/sleep filters + pre-round digest dedup run as N
        concurrent shards (fleet/shard.py — phases A/B compute only
        order-independent facts), then the canonical merge
        (``_admit_stream`` with the precomputed duplicate verdicts)
        applies admissions serially in the exact sequential order.
        Outputs are bit-identical to ``_derive_batch`` at any shard
        count (tests/test_host_shards.py, bench config 16)."""
        from ..obs.profiler import PROFILER

        recw = self.cfg.rec_width
        sleep_ctx = (
            self._sleep_ctx(batch, res)
            if batch is not None and res is not None
            else None
        )
        if self.static_independence is not None:
            # Build the lazily-cached device matrix once, on this
            # thread, before the shard threads read it concurrently.
            self.static_independence.device_matrix()
        t0 = time.perf_counter() if PROFILER.enabled else 0.0
        scan = self._sharder.scan_round(
            traces, lens, n_lanes, recw,
            independence=self.static_independence,
            sleep=self.sleep, sleep_ctx=sleep_ctx,
            explored=self._explored_digests,
            suppressed=self._suppressed_digests,
        )
        if PROFILER.enabled:
            PROFILER.host_scan(
                "dpor-host-scan", n_lanes, time.perf_counter() - t0,
                shape=f"b={n_lanes} shards={self._host_shards}",
            )
        # Same global adaptive hint as the sequential path (checkpoint
        # payloads stay identical across shard counts); the per-shard
        # ScanBuffers carry their own capacities independently.
        self._batch_size_hint = (
            max(64, (len(scan.keys) * 5) // 4),
            max(256, (len(scan.rows) * 5) // 4),
        )
        # Phase C: class-key canonicalization (the host half's dominant
        # cost on class-tracked runs) precomputed per owning shard —
        # the merge below only looks keys up.
        class_keys = self._sharder.class_round(
            scan, traces, lens, recw, self.sleep
        )
        return self._admit_stream(
            scan.rows, scan.offsets, scan.lanes, scan.keys, traces, lens,
            batch, sleep_ctx, frontier,
            known_dup=scan.known_dup, shard_ids=scan.shard_ids,
            shard_stats=scan.stats, class_keys=class_keys,
        )

    def _admit_stream(
        self, rows, offsets, lanes, keys, traces, lens,
        batch: Optional[List[Tuple]], sleep_ctx, frontier: List[Tuple],
        known_dup=None, shard_ids=None, shard_stats=None, class_keys=None,
    ) -> Tuple[int, int, int]:
        """The canonical admission loop over one round's candidate
        stream, in stream (= lane-major scan) order: digest dedup,
        sleep-class check, distance gate, frontier admission. Shared by
        the sequential and sharded paths — the sharded path passes
        ``known_dup`` (membership against the PRE-round sets, computed
        per digest-range shard) and this loop then tracks only the keys
        added DURING the merge (``round_new``), which together decide
        exactly what the sequential live-set membership check decides,
        in the same order."""
        recw = self.cfg.rec_width
        fresh_n = redundant_n = pruned_n = 0
        explored_digests = self._explored_digests
        offs = offsets.tolist()
        lane_of = np.asarray(lanes).tolist()
        # Fresh prescriptions materialize with SHARED per-lane row
        # tuples: a prescription's prefix is by construction the first
        # (mlen - 1) delivery rows of its lane in position order, so one
        # tuple list per lane serves every fresh sibling — O(refs) per
        # prescription instead of a fresh tuple per packed row.
        lane_deliv: Dict[int, Tuple[List[Tuple[int, ...]], np.ndarray]] = {}

        def deliveries_of(b: int) -> Tuple[List[Tuple[int, ...]], np.ndarray]:
            cached = lane_deliv.get(b)
            if cached is None:
                recs = traces[b, : int(lens[b]), :recw]
                pos = np.nonzero(
                    np.isin(recs[:, 0], (REC_DELIVERY, REC_TIMER))
                )[0]
                cached = ([tuple(r) for r in recs[pos].tolist()], pos)
                lane_deliv[b] = cached
            return cached

        if known_dup is None:
            candidates = range(len(keys))
            round_new = None
        else:
            # Known duplicates (vs the pre-round sets) skip in bulk —
            # the merge's per-candidate work is O(fresh), which is what
            # keeps the serial fraction small at high shard counts.
            redundant_n += int(np.count_nonzero(known_dup))
            candidates = np.flatnonzero(~known_dup).tolist()
            round_new = set()
        for k in candidates:
            key = keys[k]
            if round_new is None:
                if key in explored_digests or key in self._suppressed_digests:
                    redundant_n += 1
                    continue
            elif key in round_new:
                # Same-round duplicate: an earlier merge step already
                # explored or class-suppressed this digest — exactly
                # the sequential live-set hit.
                redundant_n += 1
                continue
            lo, hi = offs[k], offs[k + 1]
            b = lane_of[k]
            if (
                not self.pad_exploration
                and batch is not None
                and not batch[b]
            ):
                # Closed seeded exploration: padding-lane races are
                # observed but never admitted (see pad_exploration).
                pruned_n += 1
                continue
            flipped = tuple(rows[hi - 1].tolist())
            deliv, pos = deliveries_of(b)
            m = hi - lo
            presc = tuple(deliv[: m - 1]) + (flipped,)
            commit = None
            if self.sleep is not None:
                wake_row = (
                    (sleep_ctx[1][b], sleep_ctx[3][b])
                    if sleep_ctx is not None
                    else None
                )
                verdict, commit = self._sleep_class_check(
                    presc, rows[lo:hi],
                    list(pos[: m - 1]) + [None], flipped, m - 1,
                    batch[b] if batch is not None else tuple(),
                    wake_row,
                    ckey=(
                        class_keys.get(k)
                        if class_keys is not None
                        else None
                    ),
                )
                if verdict == "class":
                    self._suppressed_digests.add(key)
                    if round_new is not None:
                        round_new.add(key)
                    redundant_n += 1
                    continue
            if self._admit(presc, key, frontier):
                fresh_n += 1
                if round_new is not None:
                    round_new.add(key)
                if shard_stats is not None:
                    shard_stats[shard_ids[k]]["fresh"] += 1
                if self.sleep is not None:
                    guide = self._make_guide(deliv, m - 1, flipped, None)
                    self._guides[presc] = guide
                    if commit is not None:
                        commit(guide)
            else:
                pruned_n += 1
        return fresh_n, redundant_n, pruned_n

    def _derive_legacy(
        self, traces, lens, n_lanes: int, frontier: List[Tuple],
        batch: Optional[List[Tuple]] = None, res=None,
    ) -> Tuple[int, int, int]:
        """The pre-vectorization host path — per-lane scans, per-pair
        tuple assembly, tuple-set membership — kept as the parity
        baseline (bench config 8's host_path comparison and
        tests/test_host_path.py pin bit-identical outputs). With sleep
        sets on, applies the identical per-pair sleep filter (branch
        beyond the redundant marker, flip asleep at the branch) and
        class dedup in the same order as the batch path."""
        from ..analysis.sleep import BIG_ORDINAL, rows_content_equal

        recw = self.cfg.rec_width
        sleep_ctx = (
            self._sleep_ctx(batch, res)
            if batch is not None and res is not None
            else None
        )
        fresh_n = redundant_n = pruned_n = 0
        sleep_pruned = 0
        for lane in range(n_lanes):
            if (
                not self.pad_exploration
                and batch is not None
                and not batch[lane]
            ):
                # Closed seeded exploration (see pad_exploration): skip
                # the padding lane's harvest wholesale.
                continue
            metas, positions = racing_prescriptions_meta(
                traces[lane], int(lens[lane]), recw,
                independence=self.static_independence,
            )
            lane_deliv: Optional[List[Tuple[int, ...]]] = None
            for presc, branch, flip_ord in metas:
                if (
                    self.sleep is not None
                    and self.sleep.prune
                    and sleep_ctx is not None
                ):
                    # Per-pair sleep filter, identically placed to the
                    # batch scan's (after static, before dedup).
                    _srows, wake, slept, presc_deliv = sleep_ctx
                    flip = presc[-1]
                    asleep = branch > int(slept[lane])
                    if not asleep and branch >= int(presc_deliv[lane]):
                        lane_sleep = self._sleep_rows.get(
                            batch[lane] if batch is not None else tuple(), ()
                        )
                        for s, srow in enumerate(lane_sleep):
                            if int(wake[lane][s]) < branch:
                                continue
                            if rows_content_equal(flip, srow, recw):
                                asleep = True
                                break
                    if asleep:
                        sleep_pruned += 1
                        if self.sleep.audit:
                            self.sleep.note_pruned_prescription(presc)
                        continue
                if presc in self.explored:
                    redundant_n += 1
                    continue
                if presc in self._suppressed:
                    redundant_n += 1
                    continue
                commit = None
                if self.sleep is not None:
                    wake_row = (
                        (sleep_ctx[1][lane], sleep_ctx[3][lane])
                        if sleep_ctx is not None
                        else None
                    )
                    m = len(presc)
                    verdict, commit = self._sleep_class_check(
                        presc, np.asarray(presc, np.int32),
                        list(positions[: m - 1]) + [None], presc[-1],
                        branch,
                        batch[lane] if batch is not None else tuple(),
                        wake_row,
                    )
                    if verdict == "class":
                        self._suppressed.add(presc)
                        redundant_n += 1
                        continue
                if self._admit(presc, None, frontier):
                    fresh_n += 1
                    if self.sleep is not None:
                        if lane_deliv is None:
                            recs = traces[lane, : int(lens[lane]), :recw]
                            lane_deliv = [
                                tuple(r) for r in recs[positions].tolist()
                            ]
                        # flip_ord=None: the one guide rule both host
                        # paths share (see _make_guide) — the meta's
                        # exact ordinal resolves to the same row.
                        guide = self._make_guide(
                            lane_deliv, branch, presc[-1], None
                        )
                        self._guides[presc] = guide
                        if commit is not None:
                            commit(guide)
                    elif commit is not None:
                        commit()
                else:
                    pruned_n += 1
        if sleep_pruned:
            self.sleep.note_pruned(sleep=sleep_pruned, tier="device")
        return fresh_n, redundant_n, pruned_n

    def _note_inflight(self, outcome: str) -> None:
        self.async_stats[f"inflight_{outcome}"] += 1
        obs.counter(f"dpor.inflight_{outcome}").inc()

    @property
    def host_share(self) -> Optional[float]:
        """Fraction of frontier wall time spent host-side (planning,
        packing, racing analysis, dedup) vs blocked on device results —
        the number the vectorized host path exists to shrink. None until
        a round has run."""
        total = self.host_seconds + self.device_seconds
        return self.host_seconds / total if total > 0 else None

    @property
    def static_stats(self) -> Optional[Dict[str, int]]:
        """Static-pruning ledger by kind (None when the relation is
        off) — reported by bench configs 2/8 next to the redundant /
        distance-pruned counts."""
        if self.static_independence is None:
            return None
        return dict(self.static_independence.pruned_total)

    @property
    def sleep_stats(self) -> Optional[Dict[str, object]]:
        """Sleep-set ledger (None when sleep sets are off): prune counts
        by kind, distinct Mazurkiewicz classes among admitted
        prescriptions, and the redundancy ratio (explored over the
        class lower bound — the `bench --config 9` headline)."""
        if self.sleep is None:
            return None
        ratio = self.sleep.redundancy_ratio(len(self.explored))
        return {
            "pruned": dict(self.sleep.pruned_total),
            "classes": len(self.sleep.classes),
            "explored": len(self.explored),
            "redundancy_ratio": round(ratio, 4) if ratio else None,
        }

    def _account_device(self, secs: float) -> None:
        """Fold a device-blocked span into the ledger + obs series. The
        windowed oracle path (``explore_window``) uses this directly, so
        DPOR-oracle windows land in the report's host-share block just
        like plain ``explore`` rounds."""
        self.device_seconds += secs
        if obs.enabled():
            obs.counter("dpor.device_seconds").inc(secs)
            share = self.host_share
            if share is not None:
                obs.gauge("dpor.host_share").set(share)

    def _account_host(self, secs: float) -> None:
        """Host-side twin of ``_account_device``."""
        self.host_seconds += secs
        if obs.enabled():
            obs.counter("dpor.host_seconds").inc(secs)
            share = self.host_share
            if share is not None:
                obs.gauge("dpor.host_share").set(share)

    def _account_round(
        self, round_t0: float, device_secs: float
    ) -> Tuple[float, float]:
        """Fold one frontier round's wall time into the host/device
        split: ``device_secs`` is the harvest-blocked span, the rest of
        the iteration is host work (selection, packing, dispatch prep,
        racing analysis, dedup). Always tracked (two clock reads); the
        ``dpor.host_*`` obs series mirror it when telemetry is on.
        Returns the (host, device) seconds so the journal record can
        carry the per-round split."""
        host_secs = max(0.0, time.perf_counter() - round_t0 - device_secs)
        self._account_device(device_secs)
        self._account_host(host_secs)
        return host_secs, device_secs

    def _journal_round(
        self, host_secs: float, device_secs: float, frontier: int
    ) -> None:
        """One generation-stamped journal record per frontier round —
        the continuous-observability wire format (obs/journal.py):
        per-round wall/host/device seconds, frontier size and depth,
        fresh/redundant/pruned admission counts, in-flight economy, fork
        economy, and the round's violation codes. Called after every
        ``_account_round``; a detached journal costs one branch."""
        self.round_index += 1
        obs.profiler.PROFILER.tick_round()
        if obs.journal.JOURNAL is None:
            return
        lr = self._last_round
        rec: Dict[str, object] = {
            "round": self.round_index,
            "wall_s": round(host_secs + device_secs, 6),
            "host_s": round(host_secs, 6),
            "device_s": round(device_secs, 6),
            "batch": lr.get("batch", 0),
            "depth": lr.get("depth", 0),
            "fresh": lr.get("fresh", 0),
            "redundant": lr.get("redundant", 0),
            "distance_pruned": lr.get("distance_pruned", 0),
            "violations": lr.get("violations", []),
            "frontier": frontier,
            "interleavings": self.interleavings,
            "explored": len(self.explored),
            "inflight_hits": self.async_stats["inflight_hits"],
            "inflight_waste": self.async_stats["inflight_waste"],
        }
        if self.static_independence is not None:
            rec["static_pruned"] = int(
                sum(self.static_independence.pruned_total.values())
            )
        if self.sleep is not None:
            rec["sleep_pruned"] = int(
                sum(self.sleep.pruned_total.values())
            )
            ratio = self.sleep.redundancy_ratio(len(self.explored))
            if ratio is not None:
                rec["redundancy_ratio"] = round(ratio, 4)
        if self._forker is not None:
            st = self._forker.stats_view()
            rec["fork"] = {
                "prefix_hits": st.get("prefix_hits", 0),
                "steps_saved": st.get("steps_saved", 0),
                "forked_lanes": st.get("forked_lanes", 0),
            }
        obs.journal.emit("dpor.round", **rec)

    def explore(
        self, target_code: Optional[int] = None, max_rounds: int = 20,
        stop_on_violation: bool = True,
    ) -> Optional[Tuple[np.ndarray, int]]:
        """Returns (records, trace_len) of a violating lane, or None.
        Continues from the persisted frontier; call again for more rounds.

        ``stop_on_violation=False`` is COVERAGE mode (the fleet parity
        baseline): a hit is recorded (the FIRST one is returned) but the
        loop keeps draining rounds until the frontier empties or the
        round budget expires, so the explored/class/violation-code sets
        measure the schedule space, not the race to the first bug.

        Rounds are GENERATION-FROZEN: each round's batch is selected from
        the generation frozen at the previous generation boundary, and
        the fresh prescriptions a harvest derives join the NEXT
        generation (picked up when the current one drains). This is
        breadth-style worklist processing — deepest-first within a
        generation — and it is what makes the next round plannable before
        the current round's codes ever leave the device: the harvest
        cannot reorder the generation it was selected from. One
        deterministic exception (fork-group growth): a generation too
        small to fill a round pulls the next generation forward
        (``_merge_generations``), so equal-depth prescriptions from both
        generations batch together instead of padding the round with
        prescription-free lanes.

        With ``double_buffer`` on, round N+1's batch is selected from the
        frozen-generation remainder and dispatched as a FULL in-flight
        launch while round N's codes are still on device. The plan is
        re-checked after the harvest by re-running the (pure,
        deterministic) selection: an exact batch match means the
        in-flight launch IS the next round (per-lane keys depend only on
        the cumulative interleaving count, which padding makes
        deterministic); a mismatch — the tuner moved ``round_batch``
        mid-round — discards the launch unharvested. Either way every
        harvested round is byte-identical to the synchronous loop's,
        which follows the exact same generation policy."""
        gen = self.frontier
        pending: List[Tuple] = []  # the NEXT generation, fed by harvests
        # (batch, parts, n_real, prescs, keys) for the next round — the
        # pure round inputs ride along for poisoned-launch re-dispatch.
        inflight = None
        found = None
        for _ in range(max_rounds):
            round_t0 = time.perf_counter()
            if inflight is not None:
                batch, parts, _, r_prescs, r_keys = inflight
                inflight = None
                # A hit is an in-flight launch actually harvested as the
                # next round — adoption alone isn't enough (the budget
                # can expire first, which counts as waste, so every
                # dispatched launch lands in exactly one bucket).
                self._note_inflight("hits")
            else:
                # Fork-group growth: a generation that can't fill a round
                # pulls the next generation forward (see
                # ``_merge_generations``).
                gen, pending = self._merge_generations(gen, pending)
                if not gen:
                    break
                batch, gen = self._select_batch(gen)
                r_prescs = self._pack(batch)
                r_keys = self._round_keys(
                    len(batch), self.interleavings, batch=batch
                )
                parts = self._dispatch_round(r_prescs, r_keys, batch)
            spec = None
            if self._double_buffer and gen:
                sbatch, srest = self._select_batch(gen)
                s_prescs = self._pack(sbatch)
                s_keys = self._round_keys(
                    len(sbatch), self.interleavings + len(batch),
                    batch=sbatch,
                )
                sparts = self._dispatch_round(s_prescs, s_keys, sbatch)
                # len(gen) - len(srest) real entries precede the padding
                # in sbatch — the count the budget-expiry requeue needs
                # (a genuine root ``tuple()`` entry is falsy, so
                # truthiness can't separate it from padding). The pure
                # (prescs, keys) inputs ride along so a poisoned launch
                # can re-execute this round at harvest time.
                spec = (sbatch, sparts, len(gen) - len(srest),
                        s_prescs, s_keys)
                self._note_inflight("rounds")
            with obs.span(
                "dpor.round", batch=len(batch), frontier=len(gen)
            ):
                t_harvest = time.perf_counter()
                res = self._supervised_harvest(
                    parts, batch, r_prescs, r_keys
                )
                dev_secs = time.perf_counter() - t_harvest
            hit = self._process_round(
                res, batch, target_code, pending, frontier_extra=len(gen)
            )
            obs.gauge("dpor.frontier_size").set(len(gen) + len(pending))
            if hit is not None:
                obs.counter("dpor.violations_found").inc()
                if found is None:
                    found = hit
                if stop_on_violation:
                    if spec is not None:
                        self._note_inflight("waste")
                    h, d = self._account_round(round_t0, dev_secs)
                    self._journal_round(h, d, len(gen) + len(pending))
                    break
            if spec is not None:
                sbatch, sparts, sreal, s_prescs, s_keys = spec
                # The speculative batch was selected from the UNMERGED
                # remainder; validate against the merged pool the
                # synchronous loop would select from at its next round
                # top. A merge that changes the selection discards the
                # in-flight launch — waste, never divergence.
                mgen, mpending = self._merge_generations(gen, pending)
                abatch, arest = self._select_batch(mgen)
                if abatch == sbatch:
                    inflight = (sbatch, sparts, sreal, s_prescs, s_keys)
                    gen, pending = arest, mpending
                else:
                    self._note_inflight("waste")
            h, d = self._account_round(round_t0, dev_secs)
            self._journal_round(h, d, len(gen) + len(pending))
        if inflight is not None:
            # The round budget expired with a speculative round still on
            # device: it was never harvested, so its prescriptions go
            # back to the worklist head and the next explore() call
            # re-selects (and re-dispatches) them.
            batch, _parts, n_real, _prescs, _keys = inflight
            gen = list(batch[:n_real]) + gen
            self._note_inflight("waste")
        self.frontier = gen + pending
        return found


def explore_window(
    dpors: Sequence["DeviceDPOR"],
    target_code: Optional[int],
    max_rounds: int,
) -> List[Optional[Tuple[np.ndarray, int]]]:
    """Run several DeviceDPOR searches in lockstep, batching concurrent
    frontier rounds' device work — the engine under
    ``DeviceDPOROracle.test_window`` (IncrementalDDMin's speculative
    left/right DDMin probe pairs). Per round, every live instance's batch
    becomes ONE combined kernel launch when the instances share a kernel
    and run scratch (the common DeviceDPOROracle case: one jitted kernel
    serves every resumable instance); under prefix forking each
    instance's fork groups dispatch before any is harvested, so device
    work still overlaps across the window. Each instance's host-side
    round processing is untouched — explored sets, frontiers,
    interleavings, and per-lane keys are all per-instance, so results
    are bit-identical to running the searches sequentially."""
    n = len(dpors)
    found: List[Optional[Tuple[np.ndarray, int]]] = [None] * n
    done = [False] * n
    # Per-instance generation split, mirroring explore(): rounds select
    # from the frozen generation, fresh prescriptions join the pending
    # next generation — same policy, so committed states match the
    # sequential path exactly.
    frontiers = [list(d.frontier) for d in dpors]
    pendings: List[List[Tuple]] = [[] for _ in dpors]
    for _ in range(max_rounds):
        live = []
        for i in range(n):
            if done[i]:
                continue
            frontiers[i], pendings[i] = dpors[i]._merge_generations(
                frontiers[i], pendings[i]
            )
            if frontiers[i]:
                live.append(i)
        if not live:
            break
        staged = []
        for i in live:
            batch, frontiers[i] = dpors[i]._select_batch(frontiers[i])
            staged.append(
                (i, batch, dpors[i]._pack(batch),
                 dpors[i]._round_keys(
                     len(batch), dpors[i].interleavings, batch=batch
                 ))
            )
        combined = (
            len(staged) > 1
            and all(dpors[i]._forker is None for i, *_ in staged)
            and all(dpors[i].sleep is None for i, *_ in staged)
            and len({id(dpors[i].kernel) for i, *_ in staged}) == 1
        )
        results: List[Tuple[int, List[Tuple], LaneResult]] = []
        if combined:
            # One launch for the whole window: lanes are elementwise
            # under vmap, so concatenating the instances' (prog, presc,
            # key) rows yields exactly each instance's own round results.
            from ..persist.supervisor import SUPERVISOR

            progs = [dpors[i]._progs(len(b)) for i, b, *_ in staged]
            t_harvest = time.perf_counter()

            def _combined_launch(_attempt: int):
                r = dpors[staged[0][0]].kernel(
                    ExtProgram(*(
                        np.concatenate(
                            [np.asarray(getattr(p, f)) for p in progs]
                        )
                        for f in ExtProgram._fields
                    )),
                    np.concatenate([prescs for _, _, prescs, _ in staged]),
                    np.concatenate([np.asarray(keys) for *_, keys in staged]),
                )
                jax.block_until_ready(r.violation)
                return r

            res = SUPERVISOR.run(_combined_launch, label="dpor.launch")
            # Window launches serve several instances at once: split the
            # blocked span evenly for the per-instance host-share ledger
            # (through the accounting helper, so windowed oracle rounds
            # reach the dpor.host_share gauge + seconds counters too).
            dev_each = (time.perf_counter() - t_harvest) / len(staged)
            for i, *_ in staged:
                dpors[i]._account_device(dev_each)
            off = 0
            for i, batch, _prescs, _keys in staged:
                results.append((i, batch, LaneResult(*(
                    np.asarray(getattr(res, f))[off: off + len(batch)]
                    for f in LaneResult._fields
                ))))
                off += len(batch)
        else:
            handles = [
                (i, batch, dpors[i]._dispatch_round(prescs, keys, batch),
                 prescs, keys)
                for i, batch, prescs, keys in staged
            ]
            results = []
            for i, batch, parts, prescs, keys in handles:
                t_harvest = time.perf_counter()
                harvested = dpors[i]._supervised_harvest(
                    parts, batch, prescs, keys
                )
                dpors[i]._account_device(time.perf_counter() - t_harvest)
                results.append((i, batch, harvested))
        for i, batch, res in results:
            t_host = time.perf_counter()
            with obs.span(
                "dpor.round", batch=len(batch), frontier=len(frontiers[i])
            ):
                hit = dpors[i]._process_round(
                    res, batch, target_code, pendings[i],
                    frontier_extra=len(frontiers[i]),
                )
            dpors[i]._account_host(time.perf_counter() - t_host)
            if hit is not None:
                obs.counter("dpor.violations_found").inc()
                found[i] = hit
                done[i] = True
    for i, d in enumerate(dpors):
        d.frontier = frontiers[i] + pendings[i]
    return found
