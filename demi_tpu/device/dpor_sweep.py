"""Batched device DPOR: explore many backtrack points per kernel launch.

The reference explores one interleaving at a time (DPORwHeuristics runs a
full JVM execution per backtrack point). Here a backtrack point is a
*prescription* — a prefix of delivery records plus the flipped event — and
a whole frontier of prescriptions runs as one vmapped batch: each lane
follows its prescription (skipping absent records, divergence-tolerant)
and continues with random exploration; lanes record parent-tracked traces
(DeviceConfig.record_parents), from which the host derives the
happens-before forest and the next round's racing pairs with no
re-execution. SURVEY §7.2 step 7: the racing-pair scan is data-parallel
bit math; only the frontier priority queue stays host-side.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..config import SchedulerConfig
from ..dsl import DSLApp
from ..external_events import ExternalEvent
from ..schedulers.dpor import arvind_distance
from . import ops
from .core import (
    REC_DELIVERY,
    REC_TIMER,
    ST_DISPATCH,
    ST_DONE,
    ST_VIOLATION,
    DeviceConfig,
    ScheduleState,
    check_invariant,
    deliver_index,
    deliverable_mask,
    init_state,
)
from .encoding import lower_program
from .explore import ExtProgram, LaneResult, _finalize, make_step_fn


def make_prescribed_dispatch(app: DSLApp, cfg: DeviceConfig):
    """``prescribed_dispatch(state, presc, cursor) -> (state', cursor',
    found)``: deliver the first matchable prescribed record at/after
    ``cursor`` (skipping absent ones — divergence tolerance), with the
    per-delivery invariant check. Shared by the lane step below and the
    prefix-fork trunk runner (device/fork.py) so the two cannot drift."""
    big = jnp.int32(2**30)
    r_max = cfg.max_steps
    oh = cfg.use_onehot

    def match_record(state: ScheduleState, rec):
        is_timer_rec = rec[0] == REC_TIMER
        mask = deliverable_mask(state, cfg)
        exact = (
            (state.pool_dst == rec[2])
            & jnp.all(state.pool_msg == rec[3 : 3 + cfg.msg_width][None, :], axis=1)
            & (state.pool_timer == is_timer_rec)
            & (is_timer_rec | (state.pool_src == rec[1]))
        )
        match = mask & exact
        seqs = jnp.where(match, state.pool_seq, big)
        idx = jnp.argmin(seqs).astype(jnp.int32)
        return jnp.where(jnp.any(match), idx, jnp.int32(cfg.pool_capacity))

    def prescribed_dispatch(state: ScheduleState, presc, cursor):
        # Skip past absent prescribed records to the first matchable one.
        def cond(c3):
            c, idx, _ = c3
            rec_kind = ops.get_scalar(
                presc[:, 0], jnp.minimum(c, r_max - 1), oh
            )
            in_range = (c < r_max) & (
                (rec_kind == REC_DELIVERY) | (rec_kind == REC_TIMER)
            )
            return in_range & (idx >= cfg.pool_capacity)

        def body(c3):
            c, _, skips = c3
            idx = match_record(
                state, ops.get_row(presc, jnp.minimum(c, r_max - 1), oh)
            )
            found = idx < cfg.pool_capacity
            return (
                jnp.where(found, c, c + 1),
                idx,
                skips + jnp.where(found, 0, 1),
            )

        c, idx, _ = jax.lax.while_loop(
            cond, body, (cursor, jnp.int32(cfg.pool_capacity), jnp.int32(0))
        )
        found = idx < cfg.pool_capacity
        new_state = deliver_index(state, cfg, app, idx)
        # Per-delivery invariant checks apply during prefix replay too
        # (transient violations — e.g. two-leaders healed by a later
        # step-down — are exactly what DPOR prescribes its way into).
        if cfg.invariant_interval:
            code = jnp.where(
                found, check_invariant(new_state, app), jnp.int32(0)
            )
            new_state = new_state._replace(
                status=jnp.where(
                    code != 0, jnp.int32(ST_VIOLATION), new_state.status
                ),
                violation=jnp.where(
                    code != 0, code.astype(jnp.int32), new_state.violation
                ),
            )
        return new_state, jnp.where(found, c + 1, c), found

    return prescribed_dispatch


def make_dpor_run_lane(app: DSLApp, cfg: DeviceConfig):
    """Unjitted single-lane DPOR sweep ``run_lane(prog, prescription, key,
    start_state=None) -> LaneResult`` (composable with vmap/jit by callers
    — the XLA kernel below and the pallas twin in pallas_explore.py).
    cfg must have record_trace and record_parents on.

    Dispatch follows the prescription while records match (absent records
    are skipped — divergence tolerance), then falls back to the explore
    step's random choice. ``start_state`` (a device/fork.py
    PrefixSnapshot) resumes from a trunk's state + committed cursor with
    this lane's own rng; the default None keeps today's lowering
    byte-identical."""
    assert cfg.record_trace and cfg.record_parents
    base_step = make_step_fn(app, cfg)
    r_max = cfg.max_steps
    recw = cfg.rec_width
    prescribed_dispatch = make_prescribed_dispatch(app, cfg)

    def step(carry, presc, prog):
        state, cursor = carry

        oh = cfg.use_onehot

        in_dispatch = state.status == ST_DISPATCH
        rec_kind = ops.get_scalar(
            presc[:, 0], jnp.minimum(cursor, r_max - 1), oh
        )
        presc_active = in_dispatch & (cursor < r_max) & (
            (rec_kind == REC_DELIVERY) | (rec_kind == REC_TIMER)
        )

        def with_prescription(args):
            state, cursor = args
            new_state, new_cursor, found = prescribed_dispatch(
                state, presc, cursor
            )
            # If nothing in the prescription matched, fall back to the
            # normal (random) step from the ORIGINAL state.
            fell_back = ~found
            rnd = base_step(state, prog)
            out = jax.tree_util.tree_map(
                lambda a, b: jnp.where(fell_back, a, b), rnd, new_state
            )
            return out, new_cursor

        def without(args):
            state, cursor = args
            return base_step(state, prog), cursor

        state, cursor = jax.lax.cond(
            presc_active, with_prescription, without, (state, cursor)
        )
        return (state, cursor), None

    def run_lane(prog: ExtProgram, presc, key, start_state=None) -> LaneResult:
        if start_state is None:
            state = init_state(app, cfg, key)
            cursor0 = jnp.int32(0)
            (state, _cursor), _ = jax.lax.scan(
                lambda carry, _: step(carry, presc, prog),
                (state, cursor0), None, length=cfg.max_steps,
            )
        else:
            # Forked lane: the trunk delivered the shared-prefix records
            # (rng untouched — prescribed dispatch never splits it), so
            # resuming with this lane's key and the remaining step budget
            # is bit-identical to a scratch lane. Frozen lanes' steps are
            # no-ops, so the while_loop matches the fixed-length scan.
            state = start_state.state._replace(rng=key)

            def cond(carry):
                (s, _cur), i = carry
                return (s.status < ST_DONE) & (i < cfg.max_steps)

            def body(carry):
                sc, i = carry
                sc, _ = step(sc, presc, prog)
                return sc, i + 1

            (state, _cursor), _ = jax.lax.while_loop(
                cond, body,
                ((state, start_state.cursor), start_state.steps),
            )
        state = jax.lax.cond(
            state.status < ST_DONE, lambda s: _finalize(s, app, cfg), lambda s: s, state
        )
        return LaneResult(
            status=state.status,
            violation=state.violation,
            deliveries=state.deliveries,
            trace=state.trace,
            trace_len=state.trace_len,
            sched_hash=state.sched_hash,
        )

    return run_lane


def make_dpor_kernel(app: DSLApp, cfg: DeviceConfig, start_state: bool = False):
    """jitted ``kernel(progs[B], prescriptions[B, R, recw], keys[B]) ->
    LaneResult[B]`` (see make_dpor_run_lane). ``start_state=True`` adds a
    fourth argument — a device/fork.py PrefixSnapshot broadcast across the
    lane axis — resuming the whole batch from one trunk's state."""
    run_lane = make_dpor_run_lane(app, cfg)
    if not start_state:
        return jax.jit(jax.vmap(run_lane))
    return jax.jit(
        jax.vmap(
            lambda prog, presc, key, snap: run_lane(prog, presc, key, snap),
            in_axes=(0, 0, 0, None),
        )
    )


# ---------------------------------------------------------------------------
# Host-side racing analysis over parent-tracked records
# ---------------------------------------------------------------------------

def racing_prescriptions(
    records: np.ndarray, trace_len: int, rec_width: int
) -> List[Tuple[Tuple[int, ...], ...]]:
    """From one lane's parent-tracked trace, derive backtrack prescriptions:
    for each racing pair (i, j) — same receiver, concurrent (no
    happens-before path), j's message already created before i — the
    prescription is the delivery records before i plus j's record.

    The O(n^2) pair scan runs in the native analyzer when available
    (native/trace_analysis.cpp; pure-Python fallback is
    semantics-identical)."""
    from ..native import racing_pair_scan

    # Slice to rec_width: the scan derives the parent column from the last
    # column, so trailing padding must never reach it.
    recs = records[:trace_len, :rec_width]
    pairs = racing_pair_scan(recs)
    if len(pairs) == 0:
        return []
    is_delivery = np.isin(recs[:, 0], (REC_DELIVERY, REC_TIMER))
    positions = np.nonzero(is_delivery)[0]
    # Record tuples materialized once; prefix for branch index i is the
    # delivery tuples strictly before i.
    tuples = {int(p): tuple(int(x) for x in recs[p]) for p in positions}
    ordered = [int(p) for p in positions]
    out: List[Tuple[Tuple[int, ...], ...]] = []
    for i, j in pairs:
        k = np.searchsorted(positions, i)
        prefix = [tuples[p] for p in ordered[:k]]
        prefix.append(tuples[int(j)])
        out.append(tuple(prefix))
    return out


class DeviceDPOROracle:
    """TestOracle over DeviceDPOR: systematic batched search for a target
    violation on a given external program; positives lift to full host
    EventTraces via GuidedScheduler (BASELINE config 2 shape: bounded
    DPOR search on raft-class apps).

    Resumable: one DeviceDPOR (frontier + explored set) is kept per
    external subsequence, so repeated DDMin probes of the same subsequence
    continue the search instead of restarting (the device analog of
    ResumableDPOR, IncrementalDeltaDebugging.scala:94-122). With
    ``initial_trace`` set, each fresh instance is seeded with the recorded
    schedule's prescription; ``max_distance`` (set by IncrementalDDMin)
    caps backtracks by edit distance to it."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        config: SchedulerConfig,
        batch_size: int = 64,
        max_rounds: int = 20,
        initial_trace=None,
        autotune: bool = False,
        prefix_fork: Optional[bool] = None,
    ):
        self.app = app
        self.cfg = cfg
        self.config = config
        self.batch_size = batch_size
        self.max_rounds = max_rounds
        self.last_interleavings = 0
        self.initial_trace = initial_trace
        self.prefix_fork = prefix_fork
        self.max_distance: Optional[int] = None
        # Measurement-guided budget control: each resumable DPOR instance
        # gets its own DporBudgetTuner (frontier dynamics are
        # per-subsequence), fed by the per-round redundant/pruned counts.
        self.autotune = autotune
        self._instances: Dict[Tuple, DeviceDPOR] = {}

    def set_initial_trace(self, trace) -> None:
        self.initial_trace = trace

    @property
    def fork_stats(self) -> Optional[dict]:
        """Aggregate prefix-fork statistics across the resumable
        instances (None when forking is off) — what the CLI reports."""
        stats = [
            inst._forker.stats_view()
            for inst in self._instances.values()
            if inst._forker is not None
        ]
        if not stats:
            return None
        out: Dict[str, int] = {}
        for s in stats:
            for k, v in s.items():
                out[k] = out.get(k, 0) + v
        return out

    def tuner_summaries(self) -> List[dict]:
        """Public view of each resumable instance's budget-tuner state
        (empty unless ``autotune=True``) — what the CLI reports."""
        return [
            {
                "rounds": inst.tuner.rounds,
                "round_batch": inst.tuner.round_batch,
                "max_distance": inst.tuner.max_distance,
            }
            for inst in self._instances.values()
            if inst.tuner is not None
        ]

    def _instance(self, externals) -> DeviceDPOR:
        key = tuple(e.eid for e in externals)
        inst = self._instances.get(key)
        if inst is None:
            inst = DeviceDPOR(
                self.app, self.cfg, externals, self.batch_size,
                prefix_fork=self.prefix_fork,
            )
            if self.initial_trace is not None:
                inst.seed(
                    steering_prescription(
                        self.app, self.cfg, self.initial_trace, externals
                    )
                )
            if self.autotune:
                from ..tune import DporBudgetTuner

                inst.tuner = DporBudgetTuner(
                    batch=self.batch_size, max_distance=self.max_distance
                )
            self._instances[key] = inst
        inst.max_distance = self.max_distance
        if inst.tuner is not None:
            # The caller's budget (IncrementalDDMin's growing cap) is the
            # floor; a tuner that widened past it keeps its wider budget.
            inst.tuner.max_distance = (
                self.max_distance
                if inst.tuner.max_distance is None
                else max_distance_union(
                    inst.tuner.max_distance, self.max_distance
                )
            )
            if inst.tuner.max_distance is not None:
                inst.max_distance = inst.tuner.max_distance
        return inst

    def test(self, externals, violation_fingerprint, stats=None, init=None):
        from ..schedulers.guided import GuidedScheduler, GuideDivergence
        from .encoding import device_trace_to_guide

        if stats is not None:
            stats.record_replay()
        if violation_fingerprint is not None and not hasattr(
            violation_fingerprint, "code"
        ):
            # Device verdicts are int codes (same contract as
            # DeviceSTSOracle); don't silently widen unknown fingerprints
            # to accept-anything.
            raise TypeError(
                "DeviceDPOROracle needs an IntViolation-style fingerprint "
                f"(got {type(violation_fingerprint).__name__})"
            )
        dpor = self._instance(externals)
        target = getattr(violation_fingerprint, "code", None)
        with obs.span(
            "dpor.oracle_probe", externals=len(externals)
        ) as sp:
            found = dpor.explore(
                target_code=target, max_rounds=self.max_rounds
            )
            sp.set(found=found is not None)
        self.last_interleavings = dpor.interleavings
        if found is None:
            return None
        records, trace_len = found
        guide = device_trace_to_guide(self.app, records, trace_len)
        gs = GuidedScheduler(self.config, self.app)
        # No per-delivery check needed here: a violating device lane halts
        # at the violation, so the lifted trace's final state carries it.
        try:
            result = gs.execute_guide(guide)
        except GuideDivergence:
            obs.counter("dpor.lift_divergences").inc()
            return None  # device/host mismatch = non-reproduction
        if result.violation is None:
            return None
        if violation_fingerprint is not None and not violation_fingerprint.matches(
            result.violation
        ):
            return None
        result.trace.set_original_externals(list(externals))
        return result.trace


def max_distance_union(a: Optional[int], b: Optional[int]) -> Optional[int]:
    """The looser of two edit-distance budgets (None = unbounded)."""
    if a is None or b is None:
        return None
    return max(a, b)


def steering_prescription(
    app: DSLApp,
    cfg: DeviceConfig,
    trace,
    externals: Sequence[ExternalEvent],
) -> Tuple[Tuple[int, ...], ...]:
    """Lower a recorded violating EventTrace to a DPOR prescription (its
    delivery/timer records in order) so the first device execution replays
    the recorded schedule — the device analog of the host scheduler's
    initial-trace steering (DPORwHeuristics.scala:542-555). Prescription
    following is divergence-tolerant, so a projected subsequence's missing
    records are skipped."""
    from .encoding import lower_expected_trace

    projected = (
        trace.filter_failure_detector_messages()
        .filter_checkpoint_messages()
        .subsequence_intersection(list(externals))
    )
    recs = lower_expected_trace(app, cfg, projected, externals, cfg.max_steps)
    return tuple(
        tuple(int(x) for x in r)
        for r in recs
        if r[0] in (REC_DELIVERY, REC_TIMER)
    )


class DeviceDPOR:
    """Frontier-batched DPOR driver: rounds of B prescriptions per kernel
    launch, deepest-first priority, explored-set dedup.

    The frontier persists across ``explore`` calls (resumability — the
    device analog of DPORwHeuristics keeping depGraph/backTrack intact
    across test() calls, :225-254); ``seed`` plants an initial-trace
    prescription; ``max_distance`` caps accepted backtracks by modified
    edit distance to the seeded schedule (ArvindDistanceOrdering's metric
    over record identities)."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        program: Sequence[ExternalEvent],
        batch_size: int = 64,
        impl: Optional[str] = None,
        mesh=None,
        prefix_fork: Optional[bool] = None,
        fork_bucket: int = 8,
    ):
        assert cfg.record_trace and cfg.record_parents
        self.app = app
        self.cfg = cfg
        impl = impl or os.environ.get("DEMI_DEVICE_IMPL", "xla")
        if mesh is not None:
            # Frontier rounds sharded over the device mesh (SURVEY.md
            # §2.8: the batch axis covers EVERY batched workload, the
            # search kernels included). Rounds are padded to batch_size,
            # which must divide over the mesh axis.
            from ..parallel.mesh import LANES, shard_dpor_kernel

            if impl == "pallas":
                import sys

                print(
                    "DeviceDPOR: mesh sharding uses the XLA DPOR kernel; "
                    "ignoring impl=pallas",
                    file=sys.stderr,
                )

            if batch_size % mesh.shape[LANES]:
                raise ValueError(
                    f"batch_size {batch_size} must be a multiple of the "
                    f"mesh axis {mesh.shape[LANES]}"
                )
            self.kernel = shard_dpor_kernel(app, cfg, mesh)
        elif impl == "pallas":
            from .pallas_explore import make_dpor_kernel_pallas

            self.kernel = make_dpor_kernel_pallas(
                app, cfg, block_lanes=min(64, batch_size)
            )
        else:
            self.kernel = make_dpor_kernel(app, cfg)
        self.prog = lower_program(app, cfg, list(program))
        self.batch_size = batch_size
        # Prefix-fork (device/fork.py, DEMI_PREFIX_FORK=1 / --prefix-fork):
        # frontier prescriptions grouped by shared prefix; each group
        # resumes from a (LRU-cached) trunk snapshot instead of replaying
        # the prefix per lane. Per-lane keys are assigned by batch
        # position on both paths, so round results are bit-identical.
        from .fork import prefix_fork_enabled

        self._forker = None
        if prefix_fork_enabled(prefix_fork):
            from .fork import PrefixForker, make_dpor_prefix_runner

            if impl == "pallas" and mesh is None:
                import sys

                print(
                    "DeviceDPOR: prefix-fork trunk/fork lanes run on the "
                    "XLA DPOR kernel (bit-identical semantics)",
                    file=sys.stderr,
                )
            if mesh is None:
                self._fork_kernel = make_dpor_kernel(app, cfg, start_state=True)
            else:
                from ..parallel.mesh import shard_dpor_kernel

                self._fork_kernel = shard_dpor_kernel(
                    app, cfg, mesh, start_state=True
                )
            self._forker = PrefixForker(
                make_dpor_prefix_runner(app, cfg),
                bucket=fork_bucket,
                driver="dpor",
            )
        self._mesh = mesh
        self.explored: Set[Tuple] = set()
        self.frontier: List[Tuple] = [tuple()]
        self.explored.add(tuple())
        self.original: Optional[Tuple] = None
        self.max_distance: Optional[int] = None
        self.interleavings = 0
        # Measurement-guided budget control (demi_tpu/tune): when set, the
        # tuner sees each round's fresh/redundant/pruned prescription
        # counts and adjusts max_distance and round_batch online. The
        # kernel batch stays compiled at batch_size; round_batch caps how
        # many FRONTIER prescriptions are dispatched per round — surplus
        # lanes run prescription-free random exploration, so a
        # redundant-saturated frontier trades prescribed lanes for
        # diversification instead of re-deriving known schedules.
        self.tuner = None
        self.round_batch = batch_size

    def seed(self, prescription: Tuple[Tuple[int, ...], ...]) -> None:
        """Plant an initial prescription at the head of the frontier (and
        fix it as the edit-distance origin)."""
        self.original = prescription
        if prescription not in self.explored:
            self.explored.add(prescription)
            self.frontier.insert(0, prescription)

    def _pack(self, prescriptions: List[Tuple]) -> np.ndarray:
        r, w = self.cfg.max_steps, self.cfg.rec_width
        out = np.zeros((len(prescriptions), r, w), np.int32)
        for k, presc in enumerate(prescriptions):
            for t, rec in enumerate(presc[:r]):
                out[k, t] = rec
        return out

    def _progs(self, b: int) -> ExtProgram:
        return ExtProgram(
            op=np.broadcast_to(self.prog.op, (b,) + np.asarray(self.prog.op).shape),
            a=np.broadcast_to(self.prog.a, (b,) + np.asarray(self.prog.a).shape),
            b=np.broadcast_to(self.prog.b, (b,) + np.asarray(self.prog.b).shape),
            msg=np.broadcast_to(self.prog.msg, (b,) + np.asarray(self.prog.msg).shape),
        )

    def _launch_round(self, prescs: np.ndarray, keys, batch: List[Tuple]):
        """One frontier round's lane work, harvested to LaneResult arrays.

        Scratch mode: one whole-batch kernel launch. Prefix-fork mode:
        prescriptions grouped by bucketed shared prefix (PrefixPlanner);
        each group resumes from a cached trunk snapshot via the
        ``start_state=`` kernel, everything else (prescription-free pads
        included) runs the scratch kernel. Per-lane keys follow batch
        position on both paths, so per-lane results are bit-identical."""
        if self._forker is None or len(batch) < 2:
            res = self.kernel(self._progs(len(batch)), prescs, keys)
            jax.block_until_ready(res.violation)
            return res
        from .fork import padded_size

        keys = np.asarray(keys)
        lengths = np.asarray([len(p) for p in batch])
        groups, scratch = self._forker.plan(prescs, lengths)
        parts: List[Tuple[List[int], LaneResult]] = []

        for g in groups:
            if not self._forker.should_fork(g):
                scratch.extend(g.indices)
                continue
            trunk_presc = np.zeros_like(prescs[0])
            trunk_presc[: g.prefix_len] = prescs[g.indices[0], : g.prefix_len]
            snap, trunk_steps, hit = self._forker.trunk(
                g.key,
                ExtProgram(*(np.asarray(x) for x in self.prog)),
                trunk_presc,
                jax.random.PRNGKey(0),
            )
            full = g.indices + [g.indices[0]] * (
                padded_size(len(g.indices), self._mesh) - len(g.indices)
            )
            res_g = self._fork_kernel(
                self._progs(len(full)), prescs[full], keys[full], snap
            )
            parts.append((g.indices, res_g))
            self._forker.note_group(len(g.indices), trunk_steps, hit)
            obs.histogram("dpor.prefix_group_size").observe(len(g.indices))
        if scratch:
            full = scratch + [scratch[0]] * (
                padded_size(len(scratch), self._mesh) - len(scratch)
            )
            res_s = self.kernel(self._progs(len(full)), prescs[full], keys[full])
            parts.append((scratch, res_s))
            self._forker.note_scratch(len(scratch))
        # Merge the parts back into batch order (np arrays quack like the
        # LaneResult the harvesting loops read).
        b = len(batch)
        merged = {}
        for field in LaneResult._fields:
            ref = np.asarray(getattr(parts[0][1], field))
            merged[field] = np.zeros((b,) + ref.shape[1:], ref.dtype)
        for idx, res in parts:
            jax.block_until_ready(res.violation)
            for field in LaneResult._fields:
                merged[field][np.asarray(idx)] = np.asarray(
                    getattr(res, field)
                )[: len(idx)]
        return LaneResult(**merged)

    def explore(
        self, target_code: Optional[int] = None, max_rounds: int = 20
    ) -> Optional[Tuple[np.ndarray, int]]:
        """Returns (records, trace_len) of a violating lane, or None.
        Continues from the persisted frontier; call again for more rounds."""
        frontier = self.frontier
        for _ in range(max_rounds):
            if not frontier:
                self.frontier = frontier
                return None
            # Deepest-first; a seeded initial prescription (index 0) stays
            # first in round one regardless of length.
            head, rest = (
                ([frontier[0]], frontier[1:])
                if self.original is not None and frontier
                and frontier[0] == self.original
                else ([], frontier)
            )
            rest.sort(key=len, reverse=True)
            frontier = head + rest
            take = max(1, min(self.round_batch, self.batch_size))
            batch, frontier = frontier[:take], frontier[take:]
            # Pad to a fixed batch size so the kernel compiles once; pad
            # lanes run prescription-free (fresh random exploration) and
            # their results feed the frontier like any other lane.
            batch = batch + [tuple()] * (self.batch_size - len(batch))
            prescs = self._pack(batch)
            keys = jax.vmap(
                lambda s: jax.random.fold_in(jax.random.PRNGKey(0), s)
            )(np.arange(self.interleavings, self.interleavings + len(batch), dtype=np.uint32))
            with obs.span(
                "dpor.round", batch=len(batch), frontier=len(frontier)
            ):
                res = self._launch_round(prescs, keys, batch)
            self.interleavings += len(batch)
            if obs.enabled():
                # Device-lane totals for the round (one on-device
                # reduction, one pull) + the exploration-efficiency
                # counters optimal-DPOR tuning reads (redundant = already
                # explored, pruned = over the edit-distance cap).
                from ..obs import lane_stats as _ls

                _ls.record(
                    _ls.reduce_lanes(
                        res.status, res.violation, res.deliveries,
                        len(batch),
                        invariant_interval=self.cfg.invariant_interval,
                    ),
                    driver="dpor",
                )
                obs.counter("dpor.interleavings").inc(len(batch))
            violations = np.asarray(res.violation)
            traces = np.asarray(res.trace)
            lens = np.asarray(res.trace_len)
            hit = None
            for lane in range(len(batch)):
                code = int(violations[lane])
                if code != 0 and (target_code is None or code == target_code):
                    hit = (traces[lane], int(lens[lane]))
                    break
            # Local fresh/redundant/pruned counts: the tuner's per-round
            # signal, needed whether or not telemetry is on (the obs
            # counters still carry the cross-round totals).
            fresh_n = redundant_n = pruned_n = 0
            for lane in range(len(batch)):
                for presc in racing_prescriptions(
                    traces[lane], int(lens[lane]), self.cfg.rec_width
                ):
                    if presc in self.explored:
                        redundant_n += 1
                        obs.counter("dpor.prescriptions_redundant").inc()
                        continue
                    if (
                        self.max_distance is not None
                        and self.original is not None
                        and arvind_distance(presc, self.original)
                        > self.max_distance
                    ):
                        pruned_n += 1
                        obs.counter("dpor.prescriptions_distance_pruned").inc()
                        continue
                    fresh_n += 1
                    self.explored.add(presc)
                    frontier.append(presc)
            obs.gauge("dpor.frontier_size").set(len(frontier))
            obs.gauge("dpor.explored_set_size").set(len(self.explored))
            if self.tuner is not None:
                self.tuner.observe_round(
                    fresh=fresh_n, redundant=redundant_n, pruned=pruned_n,
                    frontier=len(frontier),
                )
                self.round_batch = self.tuner.round_batch
                if self.tuner.max_distance is not None:
                    self.max_distance = self.tuner.max_distance
            if hit is not None:
                obs.counter("dpor.violations_found").inc()
                self.frontier = frontier
                return hit
        self.frontier = frontier
        return None
