from .core import DeviceConfig, ScheduleState
from .explore import make_explore_kernel, make_single_lane_trace_kernel
from .replay import make_replay_kernel

__all__ = [
    "DeviceConfig",
    "ScheduleState",
    "make_explore_kernel",
    "make_single_lane_trace_kernel",
    "make_replay_kernel",
]
