import os as _os

import jax as _jax

# Persistent compilation cache: the CLI builds the same kernel configs
# run after run (and the batch oracle re-buckets to a handful of shapes);
# caching compiled executables on disk turns repeat compiles into loads.
# CPU is excluded by default: XLA:CPU AOT reloads warn about machine-
# feature mismatches ("could lead to SIGILL") on this host — set
# DEMI_TPU_CACHE_DIR to opt in anyway. (Backend choice is read from env,
# not jax.default_backend(), to avoid initializing a possibly-wedged axon
# backend at import time.)
try:
    _cache_dir = _os.environ.get("DEMI_TPU_CACHE_DIR")
    if _cache_dir is None and _os.environ.get("JAX_PLATFORMS") != "cpu":
        _cache_dir = _os.path.join(
            _os.path.expanduser("~"), ".cache", "demi_tpu_xla"
        )
    if _cache_dir:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:  # pragma: no cover
    pass

from .continuous import ContinuousSweepDriver
from .core import DeviceConfig, ScheduleState
from .explore import make_explore_kernel, make_single_lane_trace_kernel
from .fork import (
    PrefixCache,
    PrefixPlanner,
    PrefixSnapshot,
    fork_lanes,
    make_dpor_prefix_runner,
    make_explore_prefix_runner,
    make_replay_prefix_runner,
    prefix_fork_enabled,
)
from .pallas_explore import make_explore_kernel_pallas, make_replay_kernel_pallas
from .replay import make_replay_kernel

__all__ = [
    "ContinuousSweepDriver",
    "DeviceConfig",
    "PrefixCache",
    "PrefixPlanner",
    "PrefixSnapshot",
    "ScheduleState",
    "fork_lanes",
    "make_dpor_prefix_runner",
    "make_explore_kernel",
    "make_explore_kernel_pallas",
    "make_explore_prefix_runner",
    "make_replay_kernel_pallas",
    "make_replay_prefix_runner",
    "make_single_lane_trace_kernel",
    "make_replay_kernel",
    "prefix_fork_enabled",
]
