"""Synchronous-round dispatch: deliver one message per receiver per step.

The sequential explore step (explore.py) delivers ONE pool entry per step
but pays pool-linear mask/insert work every step — for flood workloads
(BASELINE config 5: 64-actor reliable broadcast, ~4.6k deliveries/lane)
that is ~4.6k pool-wide passes per lane. In this actor model deliveries at
DISTINCT receivers commute: a handler reads/writes only its own state row
and emits point-to-point sends, so any round that delivers at most one
entry per receiver equals the sequential schedule that delivers them in
ascending receiver order. This kernel exploits that: each dispatch step
selects one uniformly-random deliverable entry PER RECEIVER and applies
all of them with effects computed sequential-equivalently to the
ascending-receiver-id linearization — up to num_actors deliveries for one
round of pool-wide work.

What stays exact w.r.t. that linearization (pinned by tests/test_rounds.py
replaying recorded round traces through the sequential replay kernel with
``ignored_absent == 0``):
  - per-receiver handler effects, pool consumption, arrival seqs
  - the sched_hash fold (closed form of the sequential FNV fold)
  - the order-SENSITIVE timer-memory semantics (a non-timer delivery
    clears every actor's remembered timer and unparks the pool): resolved
    with prefix/suffix-or over the canonical order, including park checks
    of each receiver's re-armed timers against the memory state *at its
    position* in the linearization
  - trace records (canonical order) and DPOR parent links

What coarsens to round granularity (documented divergence from the
sequential kernel, NOT from legal system behavior): segment WaitCondition
checks and interval invariant checks run once per round, and quiescence
budgets cap the round's delivery count rather than interleaving.

Pool-capacity note: a round frees all R consumed entries BEFORE
inserting their outboxes, so the strict linearization's transient pool
peak can exceed the round lane's by up to R <= num_actors slots — a
sequential replay of a recorded round trace needs pool_capacity +
num_actors headroom to be overflow-equivalent (the round-pin soak
caught exactly this on a raft corpus: round lane DONE at 304
deliveries, same-capacity replay ST_OVERFLOW at 293).

This mode is a device-only exploration strategy with no reference
counterpart (the reference's JVM scheduler is inherently one-message-at-
a-time, Instrumenter.scala:913-1109); it widens the per-step parallelism
axis the same way vmap widens the per-lane axis — SIMD over receivers
inside SIMD over schedules.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..dsl import DSLApp
from . import ops
from .core import (
    REC_DELIVERY,
    REC_TIMER,
    ST_DISPATCH,
    ST_DONE,
    ST_INJECT,
    ST_OVERFLOW,
    ST_VIOLATION,
    DeviceConfig,
    RowProposal,
    ScheduleState,
    _append_record,
    check_invariant,
    deliverable_mask,
    fifo_head_mask,
    insert_rows,
)

_FNV_PRIME = 0x01000193
_BIG = jnp.int32(1 << 30)


def _per_dst_reduce(vals, dstv, cand, n, oh, reduce, fill):
    """Per-destination reduce of ``vals[i]`` over entries with
    ``dstv[i] == d`` and ``cand[i]`` -> [N]. Dual-mode like device/ops."""
    if oh:
        dst_oh = dstv[:, None] == jnp.arange(n)[None, :]
        table = jnp.where(dst_oh & cand[:, None], vals[:, None], fill)
        return reduce(table, axis=0)
    masked = jnp.where(cand, vals, fill)
    init = jnp.full((n,), fill, masked.dtype)
    if reduce is jnp.max:
        return init.at[dstv].max(masked)
    return init.at[dstv].min(masked)


def _gather_entry(vec, e_safe, oh, is_row=False):
    """vec[e_safe] for per-receiver entry indices e_safe[N] into pool
    arrays [P] / [P, W]."""
    if not oh:
        return vec[e_safe]
    eoh = e_safe[:, None] == jnp.arange(vec.shape[0])[None, :]
    if is_row:
        return jnp.einsum(
            "np,pw->nw", eoh.astype(jnp.int32), vec.astype(jnp.int32)
        )
    if vec.dtype == jnp.bool_:
        return jnp.any(eoh & vec[None, :], axis=1)
    return jnp.sum(jnp.where(eoh, vec[None, :], 0), axis=1)


def make_round_step_fn(app: DSLApp, cfg: DeviceConfig):
    """The round-delivery twin of explore.make_step_fn: identical injection
    phase (shared code), dispatch delivers one entry per receiver."""
    from .explore import (  # local: rounds is imported by explore
        _injection_phase,
        _precomputed,
        _segment_cond_met,
    )

    init_states, initial_rows = _precomputed(app, cfg)
    oh = cfg.use_onehot
    n, p, w = cfg.num_actors, cfg.pool_capacity, cfg.msg_width
    k_out = cfg.max_outbox
    actor_ids = jnp.arange(n, dtype=jnp.int32)
    idxv = jnp.arange(p, dtype=jnp.int32)
    # FNV prime powers c^j for j in [0, n]: the closed-form fold
    # h' = h*c^r + sum_i mix_i * c^(r-1-i) of r sequential fold steps.
    cpow = jnp.asarray(
        [pow(_FNV_PRIME, j, 1 << 32) for j in range(n + 1)], jnp.uint32
    )
    pw31 = jnp.asarray([pow(31, j, 1 << 32) for j in range(w)], jnp.uint32)
    if app.timer_tags:
        ttags = jnp.asarray(list(app.timer_tags), jnp.int32)
    else:
        ttags = None

    def step(state: ScheduleState, prog) -> ScheduleState:
        active = state.status < ST_DONE
        injecting = active & (state.status == ST_INJECT)
        dispatching = active & (state.status == ST_DISPATCH)
        inj_rec_idx = state.trace_len

        state, inj_rows, inj_rec, inj_enabled, to_dispatch = _injection_phase(
            state, cfg, app, prog, initial_rows, init_states, injecting
        )

        # ----- dispatch round ---------------------------------------------
        cond_met = _segment_cond_met(state, app, dispatching)
        cand = deliverable_mask(state, cfg) & dispatching & ~cond_met
        if cfg.srcdst_fifo:
            cand = cand & fifo_head_mask(state, cfg)
        any_deliverable = jnp.any(cand)

        # Per-receiver uniform choice: argmax of iid priorities over each
        # receiver's candidates is uniform among them; with timer_weight,
        # Gumbel-max gives the per-entry weighted analog of the sequential
        # kernel's class-weighted choice.
        key, sub = ops.rng_split(state.rng)
        if cfg.timer_weight != 1.0:
            u = jax.random.uniform(
                sub, (p,), minval=1e-20, maxval=1.0
            )
            pri = -jnp.log(-jnp.log(u)) + jnp.log(
                jnp.where(state.pool_timer, cfg.timer_weight, 1.0)
            )
        else:
            pri = jax.random.uniform(sub, (p,))
        state = state._replace(rng=jnp.where(dispatching, key, state.rng))

        dstv = state.pool_dst
        best = _per_dst_reduce(pri, dstv, cand, n, oh, jnp.max, -jnp.inf)
        delivered0 = _per_dst_reduce(
            cand, dstv, cand, n, oh, jnp.max, False
        )
        is_best = cand & (pri >= ops.gather_vec(best, dstv, oh))
        min_idx = _per_dst_reduce(
            idxv, dstv, is_best, n, oh, jnp.min, jnp.int32(p)
        )
        chosen = is_best & (idxv == ops.gather_vec(min_idx, dstv, oh))

        # Quiescence-budget cap: deliver only the first `remaining`
        # receivers of the canonical order (sequential kernel delivers
        # exactly seg_budget entries then flips the segment).
        remaining = jnp.where(
            state.seg_budget > 0,
            state.seg_budget - (state.deliveries - state.seg_start),
            _BIG,
        )
        incl0 = ops.prefix_sum(delivered0.astype(jnp.int32), oh)
        rank0 = incl0 - delivered0.astype(jnp.int32)  # exclusive
        delivered = delivered0 & (rank0 < remaining)
        chosen = chosen & ops.gather_vec(delivered, dstv, oh)
        incl = ops.prefix_sum(delivered.astype(jnp.int32), oh)
        rank = incl - delivered.astype(jnp.int32)
        r_total = jnp.sum(delivered.astype(jnp.int32))

        # Per-receiver chosen entry (p = none).
        e_idx = _per_dst_reduce(
            idxv, dstv, chosen, n, oh, jnp.min, jnp.int32(p)
        )
        e_safe = jnp.minimum(e_idx, p - 1)
        src_d = _gather_entry(state.pool_src, e_safe, oh)
        msg_d = _gather_entry(state.pool_msg, e_safe, oh, is_row=True).astype(
            jnp.int32
        )
        is_t = _gather_entry(state.pool_timer, e_safe, oh) & delivered
        crec_d = _gather_entry(state.pool_crec, e_safe, oh)

        # Handlers, vmapped over receivers; effects masked by `delivered`.
        new_rows, outbox = jax.vmap(app.handler)(
            actor_ids, state.actor_state, src_d, msg_d
        )
        actor_state = jnp.where(
            delivered[:, None], new_rows, state.actor_state
        )

        # Canonical-order timer-memory semantics. Sequential rules
        # (core.delivery_effects): a timer delivery at d remembers msg in
        # row d; a non-timer delivery zeroes the WHOLE table and unparks
        # the pool. Resolved over ascending-d order with prefix/suffix-or.
        dnt = delivered & ~is_t
        nt_incl = ops.prefix_sum(dnt.astype(jnp.int32), oh)
        nt_total = jnp.sum(dnt.astype(jnp.int32))
        nt_before = (nt_incl - dnt.astype(jnp.int32)) > 0  # strictly earlier
        nt_after = (nt_total - nt_incl) > 0  # strictly later
        any_nt = nt_total > 0
        set_row = is_t & ~nt_after  # timer survives: no later clear
        zero_row = ~set_row & any_nt
        timer_mem = jnp.where(
            set_row[:, None],
            msg_d.astype(state.timer_mem.dtype),
            jnp.where(zero_row[:, None], 0, state.timer_mem),
        )
        timer_mem_valid = set_row | (~any_nt & state.timer_mem_valid)

        # Outboxes -> proposed rows ([N, K] grid), with park checks against
        # the memory state at each receiver's position: row d is visible
        # unless an earlier receiver delivered a non-timer (only d itself
        # ever writes row d, and d's own update lands after its check).
        ob_valid = (outbox[:, :, 0] != 0) & delivered[:, None]
        ob_dst = jnp.clip(outbox[:, :, 1], 0, n - 1)
        ob_msg = outbox[:, :, 2:]
        ob_src = jnp.broadcast_to(actor_ids[:, None], (n, k_out))
        if ttags is not None:
            tag_hit = jnp.any(
                ob_msg[:, :, 0:1] == ttags[None, None, :], axis=2
            )
        else:
            tag_hit = jnp.zeros((n, k_out), bool)
        ob_timer = tag_hit & (ob_dst == actor_ids[:, None])
        check_valid = state.timer_mem_valid & ~nt_before
        mem_match = (
            jnp.all(
                ob_msg
                == state.timer_mem.astype(jnp.int32)[:, None, :],
                axis=2,
            )
            & check_valid[:, None]
        )
        ob_parked = ob_timer & mem_match & ~nt_after[:, None]

        # Consume + count + old-entry unparking.
        state = state._replace(
            actor_state=actor_state,
            pool_valid=state.pool_valid & ~chosen,
            pool_parked=jnp.where(
                any_nt, jnp.zeros_like(state.pool_parked), state.pool_parked
            ),
            timer_mem=timer_mem,
            timer_mem_valid=timer_mem_valid,
            deliveries=state.deliveries + r_total,
        )

        # Closed-form sched_hash fold of the linearization.
        mix = (
            jnp.sum(msg_d.astype(jnp.uint32) * pw31[None, :], axis=1)
            + src_d.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
            + actor_ids.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
            + is_t.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
        )
        expo = jnp.clip(r_total - 1 - rank, 0, n)
        coeff = ops.gather_vec(cpow, expo, oh)
        fold = state.sched_hash * ops.get_scalar(cpow, r_total, oh) + jnp.sum(
            jnp.where(delivered, mix * coeff, jnp.uint32(0))
        )
        state = state._replace(
            sched_hash=jnp.where(r_total > 0, fold, state.sched_hash)
        )

        # Trace records in canonical order.
        if cfg.record_trace:
            t_rows = state.trace.shape[0]
            pos = state.trace_len + rank
            kind = jnp.where(is_t, REC_TIMER, REC_DELIVERY)
            parts = [jnp.stack([kind, src_d, actor_ids], axis=1), msg_d]
            if cfg.record_parents:
                prev = state.last_rec
                parts.append(crec_d[:, None])
                parts.append(prev[:, None])
                state = state._replace(
                    last_rec=jnp.where(delivered, pos, state.last_rec)
                )
            rec = jnp.concatenate(parts, axis=1)  # [N, rec_width]
            if oh:
                pos_oh = (
                    pos[:, None] == jnp.arange(t_rows)[None, :]
                ) & delivered[:, None]
                hit = jnp.any(pos_oh, axis=0)
                contrib = jnp.einsum(
                    "nt,nr->tr", pos_oh.astype(jnp.int32), rec
                )
                trace = jnp.where(hit[:, None], contrib, state.trace)
            else:
                pos_sc = jnp.where(delivered, pos, t_rows)
                trace = state.trace.at[pos_sc].set(rec, mode="drop")
            # A round that would overrun the trace array corrupts the
            # device->host lift (trace_len past the stored rows) — flag
            # the lane as aborted instead of silently dropping records.
            state = state._replace(
                trace=trace,
                trace_len=state.trace_len + r_total,
                status=jnp.where(
                    state.trace_len + r_total > t_rows,
                    jnp.int32(ST_OVERFLOW),
                    state.status,
                ),
            )
            crec_round = jnp.broadcast_to(pos[:, None], (n, k_out)).reshape(-1)
        else:
            crec_round = jnp.zeros((n * k_out,), jnp.int32)

        # ----- the ONE pool insert for both sides -------------------------
        round_rows = RowProposal(
            valid=ob_valid.reshape(-1),
            src=ob_src.reshape(-1),
            dst=ob_dst.reshape(-1),
            timer=ob_timer.reshape(-1),
            parked=ob_parked.reshape(-1),
            msg=ob_msg.reshape(n * k_out, w),
        )
        rows = RowProposal.concat(inj_rows, round_rows)
        if cfg.record_parents:
            k_inj = inj_rows.valid.shape[0]
            crec = jnp.concatenate(
                [jnp.full((k_inj,), inj_rec_idx, jnp.int32), crec_round]
            )
        else:
            crec = None
        state = insert_rows(
            state, cfg, rows.valid, rows.src, rows.dst, rows.timer,
            rows.parked, rows.msg, crec=crec,
        )
        if cfg.record_trace:
            # Injection record (mutually exclusive with round records).
            state = _append_record(
                state, cfg, inj_rec, injecting & inj_enabled
            )

        inv_code = check_invariant(state, app)

        # Interval invariant check at round granularity: fire when the
        # round crossed an interval boundary.
        if cfg.invariant_interval:
            iv = cfg.invariant_interval
            due = (
                (state.deliveries // iv)
                > ((state.deliveries - r_total) // iv)
            ) & (r_total > 0)
            code = jnp.where(due, inv_code, jnp.int32(0))
            state = state._replace(
                status=jnp.where(
                    code != 0, jnp.int32(ST_VIOLATION), state.status
                ),
                violation=jnp.where(
                    code != 0, code.astype(jnp.int32), state.violation
                ),
            )

        # ----- status resolution (identical to the sequential step) ------
        status = jnp.where(
            injecting & (state.status == ST_INJECT) & to_dispatch,
            jnp.int32(ST_DISPATCH),
            state.status,
        )
        budget_spent = (state.seg_budget > 0) & (
            state.deliveries - state.seg_start >= state.seg_budget
        )
        quiescent = (
            dispatching
            & (~any_deliverable | budget_spent)
            & (status == ST_DISPATCH)
        )
        fin_code = inv_code
        status = jnp.where(
            quiescent,
            jnp.where(
                state.final_seg,
                jnp.where(
                    fin_code != 0, jnp.int32(ST_VIOLATION), jnp.int32(ST_DONE)
                ),
                jnp.int32(ST_INJECT),
            ),
            status,
        )
        violation = jnp.where(
            quiescent & state.final_seg,
            fin_code.astype(jnp.int32),
            state.violation,
        )
        return state._replace(status=status, violation=violation)

    return step
