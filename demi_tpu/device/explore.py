"""Vmapped random schedule exploration: the device-tier RandomScheduler.

One lane = one candidate schedule. Each scan step either injects one
external op (injection segments are atomic w.r.t. dispatch, matching the
host BaseScheduler) or delivers one uniformly-chosen deliverable pool entry.
``vmap`` advances a whole batch of lanes per XLA step; the driver shards the
batch axis over the TPU mesh (demi_tpu/parallel).

Replaces the reference hot loop (SURVEY.md §3.1: ~1 ms/message of JVM
synchronization) with a few fused gathers/scatters per delivered message
across thousands of lanes at once.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..dsl import DSLApp
from .core import (
    OP_END,
    OP_WAIT,
    ST_DISPATCH,
    ST_DONE,
    ST_INJECT,
    ST_VIOLATION,
    DeviceConfig,
    ScheduleState,
    apply_external_op,
    check_invariant,
    deliver_index,
    deliverable_mask,
    init_state,
)


class ExtProgram(NamedTuple):
    """Per-lane external program, op-encoded (see core.py)."""

    op: jnp.ndarray  # [E] int32
    a: jnp.ndarray  # [E] int32
    b: jnp.ndarray  # [E] int32
    msg: jnp.ndarray  # [E, W] int32


class LaneResult(NamedTuple):
    status: jnp.ndarray  # int32
    violation: jnp.ndarray  # int32 (0 = none)
    deliveries: jnp.ndarray  # int32
    trace: jnp.ndarray  # [T, rec_width] (zero-size when not recording)
    trace_len: jnp.ndarray  # int32


def _precomputed(app: DSLApp, cfg: DeviceConfig):
    n = cfg.num_actors
    init_states = np.stack(
        [np.asarray(app.init_state(i), np.int32) for i in range(n)]
    )
    if app.initial_msgs is not None:
        rows = [np.asarray(app.initial_msgs(i), np.int32) for i in range(n)]
        k0 = max(r.shape[0] for r in rows)
        initial_rows = np.zeros((n, k0, 2 + cfg.msg_width), np.int32)
        for i, r in enumerate(rows):
            initial_rows[i, : r.shape[0]] = r
    else:
        initial_rows = np.zeros((n, 0, 2 + cfg.msg_width), np.int32)
    return jnp.asarray(init_states), jnp.asarray(initial_rows)


def _inject_step(state: ScheduleState, prog: ExtProgram, app, cfg, init_states, initial_rows):
    e = prog.op.shape[0]
    cur = jnp.clip(state.ext_cursor, 0, e - 1)
    op = prog.op[cur]
    exhausted = state.ext_cursor >= e
    op = jnp.where(exhausted, OP_END, op)
    state = apply_external_op(
        state, cfg, app, initial_rows, init_states, op, prog.a[cur], prog.b[cur], prog.msg[cur]
    )
    new_cursor = state.ext_cursor + jnp.where(exhausted, 0, 1).astype(jnp.int32)
    to_dispatch = (op == OP_WAIT) | (op == OP_END) | (new_cursor >= e)
    status = jnp.where(
        state.status == ST_INJECT,
        jnp.where(to_dispatch, ST_DISPATCH, ST_INJECT),
        state.status,  # preserve overflow aborts from apply_external_op
    )
    # Bounded quiescence: a WAIT op carries its budget in field `a`
    # (0 = strict); a final drain — entered via OP_END *or* by running off
    # the end of a full-length program — is unlimited (stale budgets must
    # not cap it).
    seg_budget = jnp.where(
        op == OP_WAIT,
        prog.a[cur],
        jnp.where((op == OP_END) | (new_cursor >= e), 0, state.seg_budget),
    ).astype(jnp.int32)
    # Host-parity run-end semantics (reference: execution ends with the
    # segment of the LAST external event): the segment we're entering is
    # final if this op is OP_END / past-the-end, or a WAIT with nothing but
    # OP_END after it.
    next_cur = jnp.clip(new_cursor, 0, e - 1)
    next_op = jnp.where(new_cursor >= e, OP_END, prog.op[next_cur])
    final_seg = to_dispatch & (
        (op == OP_END)
        | (new_cursor >= e)
        | ((op == OP_WAIT) & (next_op == OP_END))
    )
    return state._replace(
        ext_cursor=new_cursor,
        status=status,
        seg_budget=seg_budget,
        seg_start=jnp.where(to_dispatch, state.deliveries, state.seg_start).astype(jnp.int32),
        final_seg=jnp.where(to_dispatch, final_seg, state.final_seg),
    )


def _finalize(state: ScheduleState, app, cfg) -> ScheduleState:
    code = check_invariant(state, app)
    return state._replace(
        status=jnp.where(code != 0, ST_VIOLATION, ST_DONE).astype(jnp.int32),
        violation=code.astype(jnp.int32),
    )


def _dispatch_step(state: ScheduleState, prog: ExtProgram, app, cfg):
    mask = deliverable_mask(state, cfg)
    count = jnp.sum(mask.astype(jnp.int32))
    any_deliverable = count > 0

    key, sub = jax.random.split(state.rng)
    if cfg.timer_weight != 1.0:
        # Two-stage choice: class (timer vs message) by weighted counts,
        # then uniform within class (host counterpart: FullyRandom with
        # timer_weight).
        tmask = mask & state.pool_timer
        mmask = mask & ~state.pool_timer
        tcount = jnp.sum(tmask.astype(jnp.int32))
        mcount = jnp.sum(mmask.astype(jnp.int32))
        sub, sub2 = jax.random.split(sub)
        wt = cfg.timer_weight * tcount
        p_timer = jnp.where(
            (tcount > 0) & (mcount > 0),
            wt / jnp.maximum(wt + mcount, 1e-9),
            jnp.where(tcount > 0, 1.0, 0.0),
        )
        pick_timer = jax.random.uniform(sub2) < p_timer
        mask = jnp.where(pick_timer, tmask, mmask)
        count = jnp.where(pick_timer, tcount, mcount)
    u = jax.random.uniform(sub)
    k = jnp.minimum((u * count).astype(jnp.int32), jnp.maximum(count - 1, 0))
    cum = jnp.cumsum(mask.astype(jnp.int32))
    idx = jnp.searchsorted(cum, k + 1, side="left").astype(jnp.int32)
    idx = jnp.where(any_deliverable, idx, jnp.int32(cfg.pool_capacity))
    state = state._replace(rng=key)
    state = deliver_index(state, cfg, app, idx)

    if cfg.invariant_interval:
        due = (state.deliveries % cfg.invariant_interval) == 0
        code = jnp.where(
            due & any_deliverable, check_invariant(state, app), jnp.int32(0)
        )
        state = state._replace(
            status=jnp.where(code != 0, jnp.int32(ST_VIOLATION), state.status),
            violation=jnp.where(code != 0, code.astype(jnp.int32), state.violation),
        )

    # Quiescence handling: nothing deliverable, or the segment's
    # bounded-wait budget expired. The run ends with its final segment
    # (host/reference parity — no extra drain past a trailing wait).
    budget_spent = (state.seg_budget > 0) & (
        state.deliveries - state.seg_start >= state.seg_budget
    )
    quiescent = (~any_deliverable | budget_spent) & (state.status == ST_DISPATCH)
    state = jax.lax.cond(
        quiescent & state.final_seg,
        lambda s: _finalize(s, app, cfg),
        lambda s: s._replace(
            status=jnp.where(
                quiescent, jnp.int32(ST_INJECT), s.status
            )
        ),
        state,
    )
    return state


def make_step_fn(app: DSLApp, cfg: DeviceConfig):
    init_states, initial_rows = _precomputed(app, cfg)

    def step(state: ScheduleState, prog: ExtProgram) -> ScheduleState:
        def active(state):
            return jax.lax.cond(
                state.status == ST_INJECT,
                lambda s: _inject_step(s, prog, app, cfg, init_states, initial_rows),
                lambda s: _dispatch_step(s, prog, app, cfg),
                state,
            )

        return jax.lax.cond(state.status >= ST_DONE, lambda s: s, active, state)

    return step


def make_run_lane(app: DSLApp, cfg: DeviceConfig):
    """One lane, program to completion (or step cap): the single source of
    lane semantics shared by the batch explore kernel and the single-lane
    trace kernel (the pair whose agreement the device→host lift relies on)."""
    step = make_step_fn(app, cfg)

    def run_lane(prog: ExtProgram, key) -> LaneResult:
        state = init_state(app, cfg, key)

        def body(state, _):
            return step(state, prog), None

        state, _ = jax.lax.scan(body, state, None, length=cfg.max_steps)
        # Lanes that ran out of steps mid-flight: evaluate the invariant on
        # whatever was reached (parity: host caps via max_messages then
        # checks).
        state = jax.lax.cond(
            state.status < ST_DONE, lambda s: _finalize(s, app, cfg), lambda s: s, state
        )
        return LaneResult(
            status=state.status,
            violation=state.violation,
            deliveries=state.deliveries,
            trace=state.trace,
            trace_len=state.trace_len,
        )

    return run_lane


def make_explore_kernel(app: DSLApp, cfg: DeviceConfig):
    """Returns jitted ``kernel(progs: ExtProgram[B], keys[B]) -> LaneResult[B]``.

    Each lane runs its external program to completion (or a cap) delivering
    uniformly-random deliverable messages — the device RandomScheduler."""
    return jax.jit(jax.vmap(make_run_lane(app, cfg)))


def make_single_lane_trace_kernel(app: DSLApp, cfg: DeviceConfig):
    """Single-lane explore with trace recording on: re-runs a violating
    lane's seed to extract its full delivery record for host reconstruction."""
    traced_cfg = DeviceConfig(**{**cfg.__dict__, "record_trace": True})
    return jax.jit(make_run_lane(app, traced_cfg))
