"""Vmapped random schedule exploration: the device-tier RandomScheduler.

One lane = one candidate schedule. Each scan step either injects one
external op (injection segments are atomic w.r.t. dispatch, matching the
host BaseScheduler) or delivers one uniformly-chosen deliverable pool entry.
``vmap`` advances a whole batch of lanes per XLA step; the driver shards the
batch axis over the TPU mesh (demi_tpu/parallel).

Replaces the reference hot loop (SURVEY.md §3.1: ~1 ms/message of JVM
synchronization) with a few fused gathers/scatters per delivered message
across thousands of lanes at once.
"""

from __future__ import annotations

import time
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..dsl import DSLApp
from . import ops
from .core import (
    OP_END,
    OP_WAIT,
    OP_WAITCOND,
    ST_DISPATCH,
    ST_DONE,
    ST_INJECT,
    ST_VIOLATION,
    DeviceConfig,
    RowProposal,
    ScheduleState,
    _append_record,
    alive_mask,
    check_invariant,
    delivery_effects,
    deliverable_mask,
    external_effects,
    fifo_head_mask,
    init_state,
    insert_rows,
)


class ExtProgram(NamedTuple):
    """Per-lane external program, op-encoded (see core.py)."""

    op: jnp.ndarray  # [E] int32
    a: jnp.ndarray  # [E] int32
    b: jnp.ndarray  # [E] int32
    msg: jnp.ndarray  # [E, W] int32


class LaneResult(NamedTuple):
    status: jnp.ndarray  # int32
    violation: jnp.ndarray  # int32 (0 = none)
    deliveries: jnp.ndarray  # int32
    trace: jnp.ndarray  # [T, rec_width] (zero-size when not recording)
    trace_len: jnp.ndarray  # int32
    # uint32 fingerprint of the delivered sequence (core.ScheduleState
    # .sched_hash): equal hashes = identical schedules, so sweeps can
    # report UNIQUE schedules explored, not just lanes swept.
    sched_hash: jnp.ndarray  # uint32


def broadcast_program(prog: ExtProgram, b: int) -> ExtProgram:
    """One lowered external program broadcast across a lane batch
    (NumPy views, no copies) — the ONE batch-layout rule shared by the
    DPOR frontier driver and the fleet worker's remote round execution
    (demi_tpu/fleet), so a leased round's program rows mean exactly
    what the coordinator's would."""
    return ExtProgram(
        *(
            np.broadcast_to(np.asarray(x), (b,) + np.asarray(x).shape)
            for x in prog
        )
    )


def _precomputed(app: DSLApp, cfg: DeviceConfig):
    n = cfg.num_actors
    init_states = np.stack(
        [np.asarray(app.init_state(i), np.int32) for i in range(n)]
    )
    if app.initial_msgs is not None:
        rows = [np.asarray(app.initial_msgs(i), np.int32) for i in range(n)]
        k0 = max(r.shape[0] for r in rows)
        initial_rows = np.zeros((n, k0, 2 + cfg.msg_width), np.int32)
        for i, r in enumerate(rows):
            initial_rows[i, : r.shape[0]] = r
    else:
        initial_rows = np.zeros((n, 0, 2 + cfg.msg_width), np.int32)
    return jnp.asarray(init_states), jnp.asarray(initial_rows)


def _injection_phase(
    state: ScheduleState,
    cfg: DeviceConfig,
    app: DSLApp,
    prog: ExtProgram,
    initial_rows,
    init_states,
    injecting,
):
    """The masked injection half of a fused step (inert unless `injecting`:
    op -> OP_END): applies the current external op's effects and all segment
    bookkeeping (budget/final/cond), returning the proposed pool rows for
    the shared insert. Shared verbatim by the sequential step and the
    round-delivery step (rounds.py) so the two kernels cannot drift."""
    oh = cfg.use_onehot
    e = prog.op.shape[0]
    cur = jnp.clip(state.ext_cursor, 0, e - 1)
    exhausted = state.ext_cursor >= e
    cur_op = ops.get_scalar(prog.op, cur, oh)
    op = jnp.where(injecting & ~exhausted, cur_op, OP_END)
    state, inj_rows, inj_rec, inj_enabled = external_effects(
        state, cfg, app, initial_rows, init_states,
        op,
        ops.get_scalar(prog.a, cur, oh),
        ops.get_scalar(prog.b, cur, oh),
        ops.get_row(prog.msg, cur, oh),
    )
    new_cursor = state.ext_cursor + (injecting & ~exhausted).astype(jnp.int32)
    raw_op = jnp.where(exhausted, OP_END, cur_op)
    is_wait_like = (raw_op == OP_WAIT) | (raw_op == OP_WAITCOND)
    to_dispatch = injecting & (
        is_wait_like | (raw_op == OP_END) | (new_cursor >= e)
    )
    # Bounded quiescence: a WAIT op carries its budget in field `a`, a
    # WAITCOND in field `b` (`a` is its condition id); 0 = strict. A
    # final drain — entered via OP_END *or* by running off the end of
    # a full-length program — is unlimited (stale budgets must not cap
    # it).
    seg_budget = jnp.where(
        injecting,
        jnp.where(
            raw_op == OP_WAIT,
            ops.get_scalar(prog.a, cur, oh),
            jnp.where(
                raw_op == OP_WAITCOND,
                ops.get_scalar(prog.b, cur, oh),
                jnp.where(
                    (raw_op == OP_END) | (new_cursor >= e),
                    0,
                    state.seg_budget,
                ),
            ),
        ),
        state.seg_budget,
    ).astype(jnp.int32)
    # Host-parity run-end semantics (reference: execution ends with the
    # segment of the LAST external event): the segment we're entering is
    # final if this op is OP_END / past-the-end, or a WAIT/WAITCOND with
    # nothing but OP_END after it.
    next_cur = jnp.clip(new_cursor, 0, e - 1)
    next_op = jnp.where(
        new_cursor >= e, OP_END, ops.get_scalar(prog.op, next_cur, oh)
    )
    final_seg = to_dispatch & (
        (raw_op == OP_END)
        | (new_cursor >= e)
        | (is_wait_like & (next_op == OP_END))
    )
    state = state._replace(
        ext_cursor=new_cursor,
        seg_budget=seg_budget,
        seg_start=jnp.where(
            to_dispatch, state.deliveries, state.seg_start
        ).astype(jnp.int32),
        final_seg=jnp.where(to_dispatch, final_seg, state.final_seg),
        seg_cond=jnp.where(
            to_dispatch,
            jnp.where(
                raw_op == OP_WAITCOND,
                ops.get_scalar(prog.a, cur, oh),
                jnp.int32(-1),
            ),
            state.seg_cond,
        ).astype(jnp.int32),
    )
    return state, inj_rows, inj_rec, inj_enabled, to_dispatch


def _segment_cond_met(state: ScheduleState, app: DSLApp, dispatching):
    """WaitCondition gating: True when this dispatch segment's condition
    (seg_cond >= 0) currently holds. The host checks the condition BEFORE
    each delivery and ends the segment without delivering once it holds;
    masking every candidate reproduces that exactly (the quiescence test
    sees no deliverable and flips the segment)."""
    if not app.conditions:
        return jnp.bool_(False)
    branches = [
        (lambda s, fn=fn: fn(s.actor_state, alive_mask(s))
         .astype(jnp.bool_))
        for fn in app.conditions
    ]
    cid = jnp.clip(state.seg_cond, 0, len(branches) - 1)
    return (
        (state.seg_cond >= 0)
        & jax.lax.switch(cid, branches, state)
        & dispatching
    )


def make_step_fn(app: DSLApp, cfg: DeviceConfig):
    """The fused, branchless step: injection and dispatch effects are both
    computed with masks (inert op / invalid index for the inactive side) and
    their pool inserts merge into ONE insert_rows pass per step.

    Under vmap a ``lax.cond``'s branches both execute anyway, so the old
    two-branch form paid the O(pool) insert machinery (free-slot cumsum +
    searchsorted + 7 scatters) twice per step; profiling shows these O(pool)
    passes dominate step cost. Fusing removes a full insert pass and both
    cond selects."""
    init_states, initial_rows = _precomputed(app, cfg)
    oh = cfg.use_onehot

    def step(state: ScheduleState, prog: ExtProgram) -> ScheduleState:
        # Frozen lanes (done/violation/overflow) need no outer guard: every
        # effect below is masked by `injecting`/`dispatching`, so their
        # state is bit-preserved without the selects a vmapped lax.cond
        # would pay.
        active = state.status < ST_DONE
        injecting = active & (state.status == ST_INJECT)
        dispatching = active & (state.status == ST_DISPATCH)
        rec_idx = state.trace_len  # creator link for this step's insert

        state, inj_rows, inj_rec, inj_enabled, to_dispatch = _injection_phase(
            state, cfg, app, prog, initial_rows, init_states, injecting
        )

        # ----- dispatch side (inert unless `dispatching`: idx -> P) -------
        cond_met = _segment_cond_met(state, app, dispatching)
        mask = deliverable_mask(state, cfg) & dispatching & ~cond_met
        if cfg.srcdst_fifo:
            # TCP-ordered channels: only FIFO heads (and timers) compete.
            mask = mask & fifo_head_mask(state, cfg)
        count = jnp.sum(mask.astype(jnp.int32))
        any_deliverable = count > 0

        key, sub = ops.rng_split(state.rng)  # Mosaic-safe split (pallas)
        if cfg.timer_weight != 1.0:
            # Two-stage choice: class (timer vs message) by weighted counts,
            # then uniform within class (host counterpart: FullyRandom with
            # timer_weight).
            tmask = mask & state.pool_timer
            mmask = mask & ~state.pool_timer
            tcount = jnp.sum(tmask.astype(jnp.int32))
            mcount = jnp.sum(mmask.astype(jnp.int32))
            sub, sub2 = ops.rng_split(sub)
            wt = cfg.timer_weight * tcount
            p_timer = jnp.where(
                (tcount > 0) & (mcount > 0),
                wt / jnp.maximum(wt + mcount, 1e-9),
                jnp.where(tcount > 0, 1.0, 0.0),
            )
            pick_timer = jax.random.uniform(sub2) < p_timer
            mask = jnp.where(pick_timer, tmask, mmask)
            count = jnp.where(pick_timer, tcount, mcount)
        u = jax.random.uniform(sub)
        k = jnp.minimum((u * count).astype(jnp.int32), jnp.maximum(count - 1, 0))
        idx = ops.first_true_index(mask, k, oh)
        idx = jnp.where(
            any_deliverable & dispatching, idx, jnp.int32(cfg.pool_capacity)
        )
        # rng advances only on dispatch steps (keeps the schedule stream
        # identical to the unfused kernel).
        state = state._replace(
            rng=jnp.where(dispatching, key, state.rng)
        )
        state, del_rows, del_rec = delivery_effects(state, cfg, app, idx)

        # ----- the ONE pool insert for both sides -------------------------
        rows = RowProposal.concat(inj_rows, del_rows)
        state = insert_rows(
            state, cfg, rows.valid, rows.src, rows.dst, rows.timer,
            rows.parked, rows.msg,
            crec=rec_idx if cfg.record_parents else None,
        )
        if cfg.record_trace:
            # At most one record per lane per step: the delivery's when one
            # happened, else the injection's.
            delivered = idx < cfg.pool_capacity
            rec = jnp.where(delivered, del_rec, inj_rec)
            state = _append_record(
                state, cfg, rec, delivered | (injecting & inj_enabled)
            )

        # One invariant evaluation per step serves both the interval check
        # and quiescence finalization (both see the post-delivery state).
        inv_code = check_invariant(state, app)

        # ----- interval invariant check (dispatch side) -------------------
        if cfg.invariant_interval:
            due = (state.deliveries % cfg.invariant_interval) == 0
            code = jnp.where(due & any_deliverable, inv_code, jnp.int32(0))
            state = state._replace(
                status=jnp.where(
                    code != 0, jnp.int32(ST_VIOLATION), state.status
                ),
                violation=jnp.where(
                    code != 0, code.astype(jnp.int32), state.violation
                ),
            )

        # ----- status resolution ------------------------------------------
        # Inject side: move to dispatch at segment boundaries (unless the
        # insert flipped the lane to overflow).
        status = jnp.where(
            injecting & (state.status == ST_INJECT) & to_dispatch,
            jnp.int32(ST_DISPATCH),
            state.status,
        )
        # Dispatch side: quiescence = nothing deliverable or budget spent.
        budget_spent = (state.seg_budget > 0) & (
            state.deliveries - state.seg_start >= state.seg_budget
        )
        quiescent = (
            dispatching
            & (~any_deliverable | budget_spent)
            & (status == ST_DISPATCH)
        )
        fin_code = inv_code
        status = jnp.where(
            quiescent,
            jnp.where(
                state.final_seg,
                jnp.where(fin_code != 0, jnp.int32(ST_VIOLATION), jnp.int32(ST_DONE)),
                jnp.int32(ST_INJECT),
            ),
            status,
        )
        violation = jnp.where(
            quiescent & state.final_seg, fin_code.astype(jnp.int32), state.violation
        )
        return state._replace(status=status, violation=violation)

    return step


def make_any_step_fn(app: DSLApp, cfg: DeviceConfig):
    """The cfg-selected step function: round-delivery or sequential. The
    single dispatch point for every driver (explore, continuous)."""
    if cfg.round_delivery:
        from .rounds import make_round_step_fn  # lazy: rounds imports us

        return make_round_step_fn(app, cfg)
    return make_step_fn(app, cfg)


#: The explore-kernel variant family: backend (xla | pallas) × lane axis
#: (leading | '-trailing') × loop form ('-ee' = early-exit while_loop) ×
#: delivery granularity ('-round' = round-delivery mode, whose invariant
#: checks are round-granularity — semantics-preserving only when
#: ``invariant_interval == 0``). These are the names bench.py measures
#: and the autotuner (demi_tpu/tune) selects among.
EXPLORE_VARIANTS: Tuple[str, ...] = (
    "xla",
    "xla-trailing",
    "xla-ee",
    "xla-trailing-ee",
    "xla-round-ee",
    "xla-trailing-round-ee",
    "pallas",
    "pallas-trailing",
    "pallas-trailing-ee",
)


def variant_config(cfg: DeviceConfig, name: str) -> DeviceConfig:
    """The DeviceConfig a variant name implies ('-ee' / '-round' are cfg
    toggles; backend and lane axis are kernel-construction choices)."""
    import dataclasses

    overrides = {}
    if name.endswith("-ee"):
        overrides["early_exit"] = True
    if "-round" in name:
        overrides["round_delivery"] = True
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def make_explore_kernel_variant(
    app: DSLApp, cfg: DeviceConfig, name: str, block_lanes: int = 256
):
    """Build the explore kernel for a named variant — ONE parser for the
    variant grammar, shared by bench.py's measurement matrix and the
    autotuner's calibration reps so the two can never mean different
    kernels by the same name."""
    base = name.split("-")[0]
    if base not in ("xla", "pallas"):
        raise ValueError(f"unknown explore variant {name!r}")
    lane_axis = "trailing" if "-trailing" in name else "leading"
    k_cfg = variant_config(cfg, name)
    if base == "pallas":
        from .pallas_explore import make_explore_kernel_pallas

        # Launch telemetry parity with the XLA builds (which wrap inside
        # make_explore_kernel): an unwrapped backend would read as zero
        # launches next to populated lane counters.
        return _counted_kernel(
            make_explore_kernel_pallas(
                app, k_cfg, block_lanes=block_lanes, lane_axis=lane_axis
            ),
            name,
        )
    return make_explore_kernel(app, k_cfg, lane_axis=lane_axis)


def resolve_impl(impl: str, cfg: DeviceConfig, driver: str) -> str:
    """Backend selection rule shared by the sweep drivers: round mode is
    XLA-only (pallas_explore guard), and an env/arg-forced pallas must
    degrade rather than abort — TPU bench windows are scarce."""
    if impl == "pallas" and cfg.round_delivery:
        import sys

        print(
            f"{driver}: round_delivery is XLA-only; using the XLA kernels",
            file=sys.stderr,
        )
        return "xla"
    return impl


def _finalize(state: ScheduleState, app, cfg) -> ScheduleState:
    code = check_invariant(state, app)
    return state._replace(
        status=jnp.where(code != 0, ST_VIOLATION, ST_DONE).astype(jnp.int32),
        violation=code.astype(jnp.int32),
    )


def make_run_lane(app: DSLApp, cfg: DeviceConfig):
    """One lane, program to completion (or step cap): the single source of
    lane semantics shared by the batch explore kernel and the single-lane
    trace kernel (the pair whose agreement the device→host lift relies on)."""
    step = make_any_step_fn(app, cfg)

    def run_lane(prog: ExtProgram, key, start_state=None) -> LaneResult:
        if start_state is None:
            state = init_state(app, cfg, key)
            i0 = jnp.int32(0)
        else:
            # Forked lane (device/fork.py): resume from the trunk's
            # snapshot with this lane's own rng. The trunk only ran
            # injection steps, which never consume rng, so the resumed
            # stream is bit-identical to a scratch lane's with this key.
            state = start_state.state._replace(rng=key)
            i0 = start_state.steps

        if cfg.early_exit or start_state is not None:
            # Under vmap the cond is OR-reduced across the batch: the loop
            # runs only as long as some lane is still live. (Forked lanes
            # always take this form — their remaining budget is dynamic —
            # and a frozen lane's step is a bit-exact no-op, so the result
            # matches the fixed-length scan.)
            def cond(carry):
                s, i = carry
                return (s.status < ST_DONE) & (i < cfg.max_steps)

            def wl_body(carry):
                s, i = carry
                return step(s, prog), i + 1

            state, _ = jax.lax.while_loop(
                cond, wl_body, (state, i0)
            )
        else:
            def body(state, _):
                return step(state, prog), None

            state, _ = jax.lax.scan(body, state, None, length=cfg.max_steps)
        # Lanes that ran out of steps mid-flight: evaluate the invariant on
        # whatever was reached (parity: host caps via max_messages then
        # checks).
        state = jax.lax.cond(
            state.status < ST_DONE, lambda s: _finalize(s, app, cfg), lambda s: s, state
        )
        return LaneResult(
            status=state.status,
            violation=state.violation,
            deliveries=state.deliveries,
            trace=state.trace,
            trace_len=state.trace_len,
            sched_hash=state.sched_hash,
        )

    return run_lane


def make_explore_kernel(
    app: DSLApp,
    cfg: DeviceConfig,
    lane_axis: str = "leading",
    start_state: bool = False,
):
    """Returns jitted ``kernel(progs: ExtProgram[B], keys[B]) -> LaneResult[B]``.

    Each lane runs its external program to completion (or a cap) delivering
    uniformly-random deliverable messages — the device RandomScheduler.

    ``lane_axis='trailing'`` runs the batch along the LAST axis of every
    internal array (vmap in_axes=-1): per-lane [pool]-shaped ops become
    [pool, B] with the big batch dimension minor — the axis the TPU VPU
    vectorizes — instead of a pool-sized minor axis padded to the vector
    width. The public interface is unchanged (inputs/outputs stay
    lane-leading; transposes happen inside the jit) and results are
    bit-identical.

    ``start_state=True`` adds a third kernel argument — a device/fork.py
    ``PrefixSnapshot`` broadcast across the lane axis — so a batch forks
    from one trunk's injection-prefix state with per-lane rng; False keeps
    the two-argument lowering byte-identical."""
    run_lane = make_run_lane(app, cfg)
    if start_state:
        if lane_axis != "leading":
            raise ValueError("start_state fork kernels are lane-leading only")
        return _counted_kernel(
            jax.jit(
                jax.vmap(
                    lambda prog, key, snap: run_lane(prog, key, snap),
                    in_axes=(0, 0, None),
                )
            ),
            "explore-fork",
        )
    if lane_axis == "leading":
        return _counted_kernel(jax.jit(jax.vmap(run_lane)), "explore")
    if lane_axis != "trailing":
        raise ValueError(f"lane_axis must be leading/trailing, got {lane_axis!r}")

    vmapped = jax.vmap(run_lane, in_axes=-1, out_axes=0)

    def call(progs: ExtProgram, keys) -> LaneResult:
        progs_t = ExtProgram(
            *(jnp.moveaxis(jnp.asarray(x), 0, -1) for x in progs)
        )
        keys_t = jnp.moveaxis(jnp.asarray(keys), 0, -1)
        return vmapped(progs_t, keys_t)

    return _counted_kernel(jax.jit(call), "explore-trailing")


def _counted_kernel(kernel, name: str):
    """Launch-count telemetry around a jitted lane kernel. Deliberately
    records launches/lanes only — no block_until_ready, so async dispatch
    (the double-buffered sweep path) keeps overlapping. Telemetry off =
    one branch per LAUNCH (not per lane/step), so the bench headline is
    untouched. Under the launch profiler (DEMI_PROFILE=1 /
    --profile-rounds) the async-visible DISPATCH cost — tracing plus
    enqueue, never the device wait — is attributed per launch shape."""
    from ..obs.profiler import PROFILER

    def call(progs, keys, *rest):
        if obs.enabled():
            obs.counter("device.kernel.launches").inc(kernel=name)
            obs.counter("device.kernel.lanes").inc(
                int(keys.shape[0]), kernel=name
            )
        if PROFILER.enabled:
            t0 = time.perf_counter()
            out = kernel(progs, keys, *rest)
            PROFILER.dispatch(
                name, int(keys.shape[0]), time.perf_counter() - t0
            )
            return out
        return kernel(progs, keys, *rest)

    return call


def make_single_lane_trace_kernel(app: DSLApp, cfg: DeviceConfig):
    """Single-lane explore with trace recording on: re-runs a violating
    lane's seed to extract its full delivery record for host reconstruction."""
    import dataclasses

    overrides = {"record_trace": True}
    if cfg.round_delivery and not cfg.trace_capacity:
        # Round steps append up to num_actors records each; a sweep cfg
        # without an explicit capacity gets the safe upper bound here —
        # it's ONE lane, so the [steps*N, rec_width] trace is small.
        overrides["trace_capacity"] = cfg.max_steps * cfg.num_actors
    traced_cfg = dataclasses.replace(cfg, **overrides)
    kernel = jax.jit(make_run_lane(app, traced_cfg))

    def call(prog, key):
        # Each call is one device->host lift (a violating lane re-traced
        # for host reconstruction) — worth a span: lifts bound how fast
        # sweep hits turn into minimizable experiments.
        with obs.span("device.trace_lift"):
            res = kernel(prog, key)
            jax.block_until_ready(res.trace_len)
        obs.counter("device.trace_lifts").inc()
        return res

    return call
