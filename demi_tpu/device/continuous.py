"""Continuous sweep: segment-stepped exploration with mid-flight lane
refill — the continuous-batching trick applied to schedule exploration.

A fixed-length sweep pays for its slowest lane: with heavy-tailed
schedule lengths most of the batch idles (status frozen, steps masked to
no-ops) while a few long lanes finish. Here the kernel runs SHORT
segments and returns the full state batch; between segments the host
harvests finished lanes' verdicts and re-initializes exactly those lanes
with fresh programs/keys (a masked where-merge, no recompilation). Lane
occupancy stays ~100% for any schedule-length distribution.

Per-seed results are bit-identical to the plain explore kernel: a lane's
step stream depends only on its own state/key, frozen lanes are no-ops,
and refill replaces whole lanes atomically (tests/test_continuous.py).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..dsl import DSLApp
from .core import ST_DONE, ST_VIOLATION, DeviceConfig, ScheduleState
from .explore import (
    ExtProgram,
    _finalize,
    init_state,
    make_any_step_fn,
    resolve_impl,
)

LANES = "lanes"


def _lane_sharding(mesh, axis: str = LANES):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis))


def _maybe_shard(fn, mesh, n_args: int, axis: str = LANES):
    """jit ``fn`` with every output leaf lane-sharded over ``mesh`` (all
    leaves carry the batch on their leading axis), or plain jit when mesh
    is None. Outputs-only on purpose: out_shardings *reshards* (host
    inputs get distributed on first touch, state stays resident across
    segments), while strict in_shardings would reject the zero-size
    disabled-trace leaf, which GSPMD canonicalizes to replicated no
    matter what. The refill loop's host side only ever pulls O(batch)
    status/violation/hash vectors."""
    if mesh is None:
        return jax.jit(fn)
    s = _lane_sharding(mesh, axis)
    return jax.jit(fn, out_shardings=s)


def _segment_lane_fn(app: DSLApp, cfg: DeviceConfig, seg_steps: int):
    """Per-lane segment body shared by the XLA and pallas backends: advance
    one lane by ``seg_steps`` steps, masking steps at or past the lane's
    ``cfg.max_steps`` budget (finished lanes are frozen no-ops). The
    counter rides the carry (not scan xs) so the same trace lowers under
    Mosaic, where xs-slicing has no lowering."""
    step = make_any_step_fn(app, cfg)

    def seg_lane(state: ScheduleState, prog: ExtProgram, steps_run):
        def body(carry, _):
            s, i = carry
            live = (steps_run + i) < cfg.max_steps
            s2 = step(s, prog)
            s = jax.tree_util.tree_map(
                lambda a, b: jnp.where(live, b, a), s, s2
            )
            return (s, i + 1), None

        (state, _), _ = jax.lax.scan(
            body, (state, jnp.int32(0)), None, length=seg_steps
        )
        return state

    return seg_lane


def make_segment_kernel(
    app: DSLApp, cfg: DeviceConfig, seg_steps: int, mesh=None
):
    """jitted ``(state[B], progs[B], steps_run[B]) -> state'[B]``: advance
    every lane by ``seg_steps`` steps (finished lanes are frozen no-ops).

    ``steps_run`` is each lane's step count so far; steps at or past
    ``cfg.max_steps`` are masked out per lane, so bit-parity with the plain
    explore kernel holds for ANY seg_steps, including ones that don't
    divide max_steps (a lane refilled mid-stream stops exactly on budget
    instead of running to the segment boundary).

    ``mesh`` shards the lane batch over its axis (ICI scale-out for the
    refill path; the batch must be a multiple of the mesh size)."""
    seg_lane = _segment_lane_fn(app, cfg, seg_steps)
    return _maybe_shard(jax.vmap(seg_lane), mesh, 3)


def make_segment_kernel_pallas(
    app: DSLApp,
    cfg: DeviceConfig,
    seg_steps: int,
    block_lanes: int = 128,
    interpret: Optional[bool] = None,
    mesh=None,
    axis: str = LANES,
):
    """Pallas twin of ``make_segment_kernel``: each grid cell keeps a lane
    block's full ScheduleState in VMEM for the whole segment, so the state
    round-trips HBM once per *segment* instead of once per step — the
    VMEM-residency win of the pallas explore backend composed with lane
    refill. Bit-identical to the XLA segment kernel (same
    ``_segment_lane_fn`` trace).

    Bool state leaves ride as int32 kernel operands (Mosaic mask operands
    are awkward); zero-size leaves (the disabled trace buffer) bypass the
    kernel untouched. ``mesh`` wraps the blocked call in shard_map over
    ``axis`` — each device runs the VMEM-blocked segment on its local lane
    shard."""
    from .pallas_explore import _check_pallas_cfg, _make_blocked_kernel

    if cfg.record_trace:
        raise ValueError(
            "pallas segment kernel records verdicts only (sweeps re-trace "
            "interesting lanes via the XLA single-lane kernel)"
        )
    interpret = _check_pallas_cfg(cfg, interpret)
    seg_lane = _segment_lane_fn(app, cfg, seg_steps)

    # Leaf inventory from the state/program avals.
    state_avals = jax.eval_shape(
        lambda k: init_state(app, cfg, k),
        jax.ShapeDtypeStruct((2,), jnp.uint32),
    )
    state_leaves, state_def = jax.tree_util.tree_flatten(state_avals)
    e, w = cfg.max_external_ops, cfg.msg_width
    prog_leaf_shapes = [(e,), (e,), (e,), (e, w)]
    bl = block_lanes

    kernel_idx = [
        i for i, leaf in enumerate(state_leaves) if np.prod(leaf.shape) > 0
    ]
    passthrough_idx = [
        i for i in range(len(state_leaves)) if i not in kernel_idx
    ]
    leaf_dtypes = [state_leaves[i].dtype for i in kernel_idx]

    def _wire_dtype(dt):
        return jnp.int32 if dt == jnp.bool_ else dt

    in_structs = [
        jax.ShapeDtypeStruct(
            (bl,) + tuple(state_leaves[i].shape), _wire_dtype(state_leaves[i].dtype)
        )
        for i in kernel_idx
    ]
    in_structs += [
        jax.ShapeDtypeStruct((bl,) + shape, jnp.int32)
        for shape in prog_leaf_shapes
    ]
    in_structs.append(jax.ShapeDtypeStruct((bl,), jnp.int32))
    n_state = len(kernel_idx)

    def _rebuild_state(flat_kernel, batch: int):
        leaves = [None] * len(state_leaves)
        for i, val in zip(kernel_idx, flat_kernel):
            leaves[i] = val
        for i in passthrough_idx:
            aval = state_leaves[i]
            leaves[i] = jnp.zeros((batch,) + tuple(aval.shape), aval.dtype)
        return jax.tree_util.tree_unflatten(state_def, leaves)

    def block_fn(*flat):
        state_flat = [
            v.astype(dt) for v, dt in zip(flat[:n_state], leaf_dtypes)
        ]
        op, a, b, msg = flat[n_state : n_state + 4]
        steps_run = flat[n_state + 4]
        state = _rebuild_state(state_flat, bl)
        out = jax.vmap(seg_lane)(
            state, ExtProgram(op=op, a=a, b=b, msg=msg), steps_run
        )
        out_flat = jax.tree_util.tree_leaves(out)
        return tuple(
            out_flat[i].astype(_wire_dtype(state_leaves[i].dtype))
            for i in kernel_idx
        )

    blocked = _make_blocked_kernel(block_fn, in_structs, bl, interpret)

    def call(state: ScheduleState, progs: ExtProgram, steps_run):
        batch = steps_run.shape[0]
        flat = jax.tree_util.tree_leaves(state)
        ins = [
            flat[i].astype(_wire_dtype(state_leaves[i].dtype))
            for i in kernel_idx
        ]
        ins += [progs.op, progs.a, progs.b, progs.msg]
        ins.append(steps_run.astype(jnp.int32))
        outs = blocked(*ins)
        outs = [v.astype(dt) for v, dt in zip(outs, leaf_dtypes)]
        return _rebuild_state(outs, batch)

    if mesh is None:
        return jax.jit(call)

    from jax.sharding import PartitionSpec as P

    lane = P(axis)
    spec = jax.tree_util.tree_map(lambda _: lane, state_avals)
    prog_spec = ExtProgram(op=lane, a=lane, b=lane, msg=lane)
    smapped = jax.shard_map(
        call,
        mesh=mesh,
        in_specs=(spec, prog_spec, lane),
        out_specs=spec,
        # pallas_call outputs carry no varying-mesh-axes annotation;
        # lanes are fully independent, nothing is replicated.
        check_vma=False,
    )
    sharding = _lane_sharding(mesh, axis)

    def sharded_call(state, progs, steps_run):
        out = smapped(state, progs, steps_run)
        # Zero-size passthrough leaves (the disabled trace buffer) fall
        # out of shard_map replicated; re-constrain the whole tree so the
        # strictly-sharded refill/finalize jits accept it.
        return jax.lax.with_sharding_constraint(out, sharding)

    return jax.jit(sharded_call)


def make_init_kernel(app: DSLApp, cfg: DeviceConfig, mesh=None):
    """jitted ``keys[B] -> ScheduleState[B]`` batch initializer."""
    return _maybe_shard(
        jax.vmap(lambda key: init_state(app, cfg, key)), mesh, 1
    )


def make_refill_kernel(app: DSLApp, cfg: DeviceConfig, mesh=None):
    """jitted ``(state[B], refill[B] bool, fresh[B]) -> state'[B]``:
    lanes with ``refill`` set are replaced by the fresh state wholesale."""

    def refill(state: ScheduleState, mask, fresh: ScheduleState):
        def merge(old, new):
            m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, new, old)

        return jax.tree_util.tree_map(merge, state, fresh)

    return _maybe_shard(refill, mesh, 3)


def make_finalize_kernel(app: DSLApp, cfg: DeviceConfig, mesh=None):
    """jitted forced finalization for lanes that exhausted their step
    budget mid-flight (parity: the plain kernel's run-out path)."""

    def fin(state: ScheduleState):
        return jax.lax.cond(
            state.status < ST_DONE,
            lambda s: _finalize(s, app, cfg),
            lambda s: s,
            state,
        )

    return _maybe_shard(jax.vmap(fin), mesh, 1)


class ContinuousSweepDriver:
    """Seed-space sweep with continuous refill.

    ``program_gen(seed) -> [ExternalEvent]`` as in SweepDriver; verdicts
    per seed are identical to running each seed through the plain explore
    kernel with ``PRNGKey(seed)``."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        program_gen: Callable,
        batch: int = 256,
        seg_steps: int = 32,
        key_fn: Optional[Callable] = None,
        impl: str = "xla",
        mesh=None,
        block_lanes: int = 128,
        program_key: Optional[Callable] = None,
    ):
        from .encoding import lower_program, stack_programs

        self.app = app
        self.cfg = cfg
        self.program_gen = program_gen
        self.batch = batch
        self.seg_steps = seg_steps
        if mesh is not None and batch % mesh.size:
            raise ValueError(
                f"continuous batch {batch} must be a multiple of the mesh "
                f"size {mesh.size}"
            )
        # key_fn(seed) -> PRNGKey; default matches the plain explore
        # kernel driven with PRNGKey(seed). SweepDriver passes its
        # fold_in(base_key, seed) scheme for cross-mode parity.
        self.key_fn = key_fn or jax.random.PRNGKey
        # program_key(seed) -> hashable: callers whose generator is
        # periodic in seed (config-5 style sweeps) pass the period key so
        # refill skips re-lowering — at 1e5+ lanes host-side lowering
        # otherwise dominates the harvest path. The RNG stream still uses
        # the raw seed, so equal programs keep distinct schedules.
        if program_key is None:
            self._lower = lambda seed: lower_program(
                app, cfg, program_gen(seed)
            )
        else:
            memo: dict = {}

            def _lower_memo(seed):
                k = program_key(seed)
                prog = memo.get(k)
                if prog is None:
                    prog = memo[k] = lower_program(
                        app, cfg, program_gen(seed)
                    )
                return prog

            self._lower = _lower_memo
        self._stack = stack_programs
        impl = resolve_impl(impl, cfg, "ContinuousSweepDriver")
        if impl == "pallas":
            self.segment = make_segment_kernel_pallas(
                app, cfg, seg_steps, block_lanes=block_lanes, mesh=mesh
            )
        elif impl == "xla":
            self.segment = make_segment_kernel(app, cfg, seg_steps, mesh=mesh)
        else:
            raise ValueError(f"impl must be 'xla' or 'pallas', got {impl!r}")
        self.mesh = mesh
        self.init = make_init_kernel(app, cfg, mesh=mesh)
        self.refill = make_refill_kernel(app, cfg, mesh=mesh)
        self.finalize = make_finalize_kernel(app, cfg, mesh=mesh)
        # Occupancy accounting for the last _run: lane-steps spent with a
        # live (unfinished, unparked) lane vs total lane-steps scanned —
        # the number the compaction exists to maximize. A fixed sweep
        # without early exit scans lanes * max_steps; compare
        # last_total_lane_steps against that to see the saving.
        self.last_occupancy: Optional[float] = None
        self.last_total_lane_steps: int = 0
        self.last_live_lane_steps: int = 0
        # Wall-clock attribution for the last _run: device-segment time
        # (dispatch + the status sync) vs everything else (harvest,
        # program lowering, refill) — the scale-rehearsal metric for how
        # much the host-side refill path costs.
        self.last_segment_seconds: float = 0.0
        self.last_harvest_seconds: float = 0.0

    def _record_round_stats(self, state, finished, vio) -> None:
        """Fold one harvest round's finished lanes into the registry
        (device.lane.* counters, driver=continuous) plus refill/occupancy
        gauges. Called at most once per segment round, only when
        telemetry is enabled. Shares reduce_lanes with the chunked/DPOR
        drivers — one definition of every counter — masked to the lanes
        finishing THIS round (each lane is counted exactly once, at
        harvest)."""
        from ..obs import lane_stats as _ls

        _ls.record(
            _ls.reduce_lanes(
                np.asarray(state.status), vio, np.asarray(state.deliveries),
                finished,
                invariant_interval=self.cfg.invariant_interval,
            ),
            driver="continuous",
        )
        obs.counter("device.continuous.rounds").inc()
        if self.last_occupancy is not None:
            obs.gauge("device.continuous.occupancy").set(self.last_occupancy)

    def time_to_first_violation(self, max_lanes: int = 1_000_000):
        """Wall-clock seconds until the first violating lane finishes (the
        BASELINE.md headline #2 shape, continuous-refill form). Returns
        (seconds, seed) or (None, None) if ``max_lanes`` seeds stay clean."""
        import time

        t0 = time.perf_counter()
        for seed, code in self.sweep_iter(max_lanes):
            if code != 0:
                return time.perf_counter() - t0, seed
        return None, None

    def sweep_iter(self, total_lanes: int, seeds: Optional[Sequence[int]] = None):
        """Generator form of ``sweep``: yields (seed, violation_code) as
        lanes finish."""
        for seed, _st, code, _h in self._run(total_lanes, seeds=seeds):
            yield seed, code

    def sweep(self, total_lanes: int = 0, seeds: Optional[Sequence[int]] = None):
        """Run ``total_lanes`` sequential seeds — or an explicit ``seeds``
        sequence (a distributed rank's strided partition, a replay list) —
        returning (statuses, violations) keyed by seed."""
        statuses, violations = {}, {}
        for seed, st, code, _h in self._run(total_lanes, seeds=seeds):
            statuses[seed] = st
            violations[seed] = code
        return statuses, violations

    def _run(self, total_lanes: int, seeds: Optional[Sequence[int]] = None):
        """Per-lane view over ``_run_batches``: yields one
        ``(seed, status, violation_code, sched_hash)`` tuple per finished
        lane (the original surface; batch consumers use the arrays)."""
        for seed_a, st_a, code_a, h_a in self._run_batches(
            total_lanes, seeds=seeds
        ):
            for k in range(len(seed_a)):
                yield (
                    int(seed_a[k]), int(st_a[k]), int(code_a[k]),
                    int(h_a[k]),
                )

    def _run_batches(
        self, total_lanes: int, seeds: Optional[Sequence[int]] = None
    ):
        """The harvest loop, yielding one ``(seeds, statuses, codes,
        hashes)`` array quadruple per segment round (only rounds that
        retired lanes yield). Array-granular retirement is what lets the
        SweepDriver's harvest accumulation stay vectorized — per-lane
        Python tuples exist only for callers that ask (``_run``)."""
        seed_list = (
            list(range(total_lanes)) if seeds is None else list(seeds)
        )
        total_lanes = len(seed_list)
        if total_lanes == 0:
            return
        b = min(self.batch, total_lanes)
        if self.mesh is not None:
            # Lane-sharded kernels need a mesh-multiple batch; surplus
            # lanes start inert (never yielded, never refilled).
            align = self.mesh.size
            b = max(align, ((b + align - 1) // align) * align)
        live_lane_steps = 0
        total_lane_steps = 0

        # Vectorized key derivation: the per-seed Python loop costs
        # 10s of ms per refill round at big batches (a visible slice of
        # harvest overhead at 1e5+ lanes). Falls back to the loop for
        # key_fns that don't trace.
        vkeys = getattr(self, "_vkeys", None)
        if vkeys is None:
            try:
                vkeys = jax.jit(jax.vmap(self.key_fn))
                vkeys(jnp.arange(2, dtype=jnp.uint32))  # traceability probe
            except Exception:
                vkeys = lambda seeds: jnp.stack(  # noqa: E731
                    [self.key_fn(int(s)) for s in seeds]
                )
            self._vkeys = vkeys

        def keys_for(seeds):
            return self._vkeys(jnp.asarray(seeds, jnp.uint32))

        n_live = min(b, total_lanes)
        # Lane i runs seed_list[i]; surplus (mesh-alignment) lanes run the
        # first seed inertly — never yielded, never refilled.
        lane_seed = [
            seed_list[i] if i < n_live else seed_list[0] for i in range(b)
        ]
        next_idx = n_live  # next position in seed_list to hand out
        progs_host: List = [self._lower(s) for s in lane_seed]
        progs = self._stack(progs_host)
        state = self.init(keys_for(lane_seed))
        steps_run = np.zeros(b, np.int64)
        done_count = 0
        active = np.arange(b) < n_live

        self.last_segment_seconds = 0.0
        self.last_harvest_seconds = 0.0
        while done_count < total_lanes:
            total_lane_steps += b * self.seg_steps
            live_lane_steps += int(active.sum()) * self.seg_steps
            self.last_occupancy = live_lane_steps / total_lane_steps
            self.last_total_lane_steps = total_lane_steps
            self.last_live_lane_steps = live_lane_steps
            t_seg = time.perf_counter()
            state = self.segment(
                state, progs, jnp.asarray(steps_run, jnp.int32)
            )
            # The status pull is the sync point: everything up to it is
            # device-segment time, the rest of the iteration is harvest.
            _status_sync = np.asarray(state.status)
            t_harvest = time.perf_counter()
            self.last_segment_seconds += t_harvest - t_seg
            steps_run = np.minimum(
                steps_run + self.seg_steps, self.cfg.max_steps
            )
            # Budget exhaustion: force-finalize overdue live lanes (the
            # plain kernel's run-out-of-steps semantics).
            status = _status_sync
            overdue = (
                active & (status < ST_DONE) & (steps_run >= self.cfg.max_steps)
            )
            if overdue.any():
                finalized = self.finalize(state)
                state = self.refill(state, jnp.asarray(overdue), finalized)
                status = np.asarray(state.status)
            finished = active & (status >= ST_DONE)
            out = None
            if finished.any():
                vio = np.asarray(state.violation)
                sh = np.asarray(state.sched_hash)
                if obs.enabled():
                    # Round-granularity lane telemetry: the status pull
                    # above is the round's one sync point; deliveries ride
                    # the same harvest (never per segment step).
                    self._record_round_stats(state, finished, vio)
                fin = np.flatnonzero(finished)
                # Seeds gathered BEFORE refill rewrites lane_seed.
                out = (
                    np.asarray(lane_seed, np.int64)[fin],
                    status[fin].copy(), vio[fin].copy(), sh[fin].copy(),
                )
                done_count += len(fin)
                # Refill finished lanes with fresh seeds (or park them).
                refill_lanes = set(
                    int(x) for x in np.flatnonzero(finished)[
                        : max(0, total_lanes - next_idx)
                    ]
                )
                for lane in np.flatnonzero(finished):
                    active[lane] = False
                if refill_lanes:
                    fresh_seeds = seed_list[
                        next_idx : next_idx + len(refill_lanes)
                    ]
                    next_idx += len(refill_lanes)
                    mask = np.zeros(b, bool)
                    full_seeds = []
                    k = 0
                    for lane in range(b):
                        if lane in refill_lanes and k < len(fresh_seeds):
                            mask[lane] = True
                            lane_seed[lane] = fresh_seeds[k]
                            progs_host[lane] = self._lower(fresh_seeds[k])
                            full_seeds.append(fresh_seeds[k])
                            active[lane] = True
                            steps_run[lane] = 0
                            k += 1
                        else:
                            full_seeds.append(lane_seed[lane])
                    progs = self._stack(progs_host)
                    fresh = self.init(keys_for(full_seeds))
                    state = self.refill(state, jnp.asarray(mask), fresh)
            # Yield after the timing stop so caller time (a generator
            # consumer may do arbitrary work per item) never counts as
            # harvest overhead.
            self.last_harvest_seconds += time.perf_counter() - t_harvest
            if out is not None:
                yield out
