"""Continuous sweep: segment-stepped exploration with mid-flight lane
refill — the continuous-batching trick applied to schedule exploration.

A fixed-length sweep pays for its slowest lane: with heavy-tailed
schedule lengths most of the batch idles (status frozen, steps masked to
no-ops) while a few long lanes finish. Here the kernel runs SHORT
segments and returns the full state batch; between segments the host
harvests finished lanes' verdicts and re-initializes exactly those lanes
with fresh programs/keys (a masked where-merge, no recompilation). Lane
occupancy stays ~100% for any schedule-length distribution.

Per-seed results are bit-identical to the plain explore kernel: a lane's
step stream depends only on its own state/key, frozen lanes are no-ops,
and refill replaces whole lanes atomically (tests/test_continuous.py).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..dsl import DSLApp
from .core import ST_DONE, ST_VIOLATION, DeviceConfig, ScheduleState
from .explore import ExtProgram, _finalize, init_state, make_step_fn


def make_segment_kernel(app: DSLApp, cfg: DeviceConfig, seg_steps: int):
    """jitted ``(state[B], progs[B], steps_run[B]) -> state'[B]``: advance
    every lane by ``seg_steps`` steps (finished lanes are frozen no-ops).

    ``steps_run`` is each lane's step count so far; steps at or past
    ``cfg.max_steps`` are masked out per lane, so bit-parity with the plain
    explore kernel holds for ANY seg_steps, including ones that don't
    divide max_steps (a lane refilled mid-stream stops exactly on budget
    instead of running to the segment boundary)."""
    step = make_step_fn(app, cfg)

    def run_segment(
        state: ScheduleState, prog: ExtProgram, steps_run
    ) -> ScheduleState:
        def body(s, i):
            live = (steps_run + i) < cfg.max_steps
            s2 = step(s, prog)
            s = jax.tree_util.tree_map(
                lambda a, b: jnp.where(live, b, a), s, s2
            )
            return s, None

        state, _ = jax.lax.scan(body, state, jnp.arange(seg_steps))
        return state

    return jax.jit(jax.vmap(run_segment))


def make_init_kernel(app: DSLApp, cfg: DeviceConfig):
    """jitted ``keys[B] -> ScheduleState[B]`` batch initializer."""
    return jax.jit(jax.vmap(lambda key: init_state(app, cfg, key)))


def make_refill_kernel(app: DSLApp, cfg: DeviceConfig):
    """jitted ``(state[B], refill[B] bool, fresh[B]) -> state'[B]``:
    lanes with ``refill`` set are replaced by the fresh state wholesale."""

    def refill(state: ScheduleState, mask, fresh: ScheduleState):
        def merge(old, new):
            m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, new, old)

        return jax.tree_util.tree_map(merge, state, fresh)

    return jax.jit(refill)


def make_finalize_kernel(app: DSLApp, cfg: DeviceConfig):
    """jitted forced finalization for lanes that exhausted their step
    budget mid-flight (parity: the plain kernel's run-out path)."""

    def fin(state: ScheduleState):
        return jax.lax.cond(
            state.status < ST_DONE,
            lambda s: _finalize(s, app, cfg),
            lambda s: s,
            state,
        )

    return jax.jit(jax.vmap(fin))


class ContinuousSweepDriver:
    """Seed-space sweep with continuous refill.

    ``program_gen(seed) -> [ExternalEvent]`` as in SweepDriver; verdicts
    per seed are identical to running each seed through the plain explore
    kernel with ``PRNGKey(seed)``."""

    def __init__(
        self,
        app: DSLApp,
        cfg: DeviceConfig,
        program_gen: Callable,
        batch: int = 256,
        seg_steps: int = 32,
        key_fn: Optional[Callable] = None,
    ):
        from .encoding import lower_program, stack_programs

        self.app = app
        self.cfg = cfg
        self.program_gen = program_gen
        self.batch = batch
        self.seg_steps = seg_steps
        # key_fn(seed) -> PRNGKey; default matches the plain explore
        # kernel driven with PRNGKey(seed). SweepDriver passes its
        # fold_in(base_key, seed) scheme for cross-mode parity.
        self.key_fn = key_fn or jax.random.PRNGKey
        self._lower = lambda seed: lower_program(
            app, cfg, program_gen(seed)
        )
        self._stack = stack_programs
        self.segment = make_segment_kernel(app, cfg, seg_steps)
        self.init = make_init_kernel(app, cfg)
        self.refill = make_refill_kernel(app, cfg)
        self.finalize = make_finalize_kernel(app, cfg)
        # Occupancy accounting for the last _run: lane-steps spent with a
        # live (unfinished, unparked) lane vs total lane-steps scanned —
        # the number the compaction exists to maximize. A fixed sweep
        # without early exit scans lanes * max_steps; compare
        # last_total_lane_steps against that to see the saving.
        self.last_occupancy: Optional[float] = None
        self.last_total_lane_steps: int = 0
        self.last_live_lane_steps: int = 0

    def time_to_first_violation(self, max_lanes: int = 1_000_000):
        """Wall-clock seconds until the first violating lane finishes (the
        BASELINE.md headline #2 shape, continuous-refill form). Returns
        (seconds, seed) or (None, None) if ``max_lanes`` seeds stay clean."""
        import time

        t0 = time.perf_counter()
        for seed, code in self.sweep_iter(max_lanes):
            if code != 0:
                return time.perf_counter() - t0, seed
        return None, None

    def sweep_iter(self, total_lanes: int):
        """Generator form of ``sweep``: yields (seed, violation_code) as
        lanes finish."""
        for seed, _st, code, _h in self._run(total_lanes):
            yield seed, code

    def sweep(self, total_lanes: int):
        """Run ``total_lanes`` seeds; returns (statuses, violations) keyed
        by seed."""
        statuses, violations = {}, {}
        for seed, st, code, _h in self._run(total_lanes):
            statuses[seed] = st
            violations[seed] = code
        return statuses, violations

    def _run(self, total_lanes: int):
        b = min(self.batch, total_lanes)
        next_seed = 0
        live_lane_steps = 0
        total_lane_steps = 0

        def keys_for(seeds):
            return jnp.stack([self.key_fn(s) for s in seeds])

        lane_seed = list(range(b))
        next_seed = b
        progs_host: List = [self._lower(s) for s in lane_seed]
        progs = self._stack(progs_host)
        state = self.init(keys_for(lane_seed))
        steps_run = np.zeros(b, np.int64)
        done_count = 0
        active = np.ones(b, bool)

        while done_count < total_lanes:
            total_lane_steps += b * self.seg_steps
            live_lane_steps += int(active.sum()) * self.seg_steps
            self.last_occupancy = live_lane_steps / total_lane_steps
            self.last_total_lane_steps = total_lane_steps
            self.last_live_lane_steps = live_lane_steps
            state = self.segment(
                state, progs, jnp.asarray(steps_run, jnp.int32)
            )
            steps_run = np.minimum(
                steps_run + self.seg_steps, self.cfg.max_steps
            )
            # Budget exhaustion: force-finalize overdue live lanes (the
            # plain kernel's run-out-of-steps semantics).
            status = np.asarray(state.status)
            overdue = (
                active & (status < ST_DONE) & (steps_run >= self.cfg.max_steps)
            )
            if overdue.any():
                finalized = self.finalize(state)
                state = self.refill(state, jnp.asarray(overdue), finalized)
                status = np.asarray(state.status)
            finished = active & (status >= ST_DONE)
            if not finished.any():
                continue
            vio = np.asarray(state.violation)
            sh = np.asarray(state.sched_hash)
            for lane in np.flatnonzero(finished):
                yield (
                    lane_seed[lane], int(status[lane]), int(vio[lane]),
                    int(sh[lane]),
                )
                done_count += 1
            # Refill finished lanes with fresh seeds (or park them).
            refill_lanes = [
                int(x) for x in np.flatnonzero(finished)
            ][: max(0, total_lanes - next_seed)]
            for lane in np.flatnonzero(finished):
                active[lane] = False
            if refill_lanes:
                fresh_seeds = list(
                    range(next_seed, next_seed + len(refill_lanes))
                )
                next_seed += len(refill_lanes)
                mask = np.zeros(b, bool)
                full_seeds = []
                k = 0
                for lane in range(b):
                    if lane in refill_lanes and k < len(fresh_seeds):
                        mask[lane] = True
                        lane_seed[lane] = fresh_seeds[k]
                        progs_host[lane] = self._lower(fresh_seeds[k])
                        full_seeds.append(fresh_seeds[k])
                        active[lane] = True
                        steps_run[lane] = 0
                        k += 1
                    else:
                        full_seeds.append(lane_seed[lane])
                progs = self._stack(progs_host)
                fresh = self.init(keys_for(full_seeds))
                state = self.refill(state, jnp.asarray(mask), fresh)
