"""Device-tier core: tensor encoding of one schedule's full state, and the
shared transition machinery (external-op injection, message delivery, pool
maintenance).

This is the TPU-native replacement for the reference's per-message JVM
dispatch cycle (SURVEY.md §3.1 hot loop, Instrumenter.scala:913-1109): a
schedule's *entire* interposition state — actor states, the pending-message
pool, partitions, timers — lives in fixed-shape int32/bool arrays, and one
``step`` advances one schedule by one event. ``vmap(step)`` advances
thousands of candidate interleavings in lockstep; ``lax.scan`` drives the
step loop under jit.

Dynamic structures become capacity-bounded arrays + masks (SURVEY.md §7.3):
pool overflow surfaces as an explicit per-lane abort status, never silent
truncation.

Record encoding (shared by explore *output* traces and replay *input*
schedules): int32 rows ``(kind, a, b, msg[W])`` with
  kind 0            = none / padding
  kind 1            = message delivery   (a=src, b=dst)
  kind 2            = timer delivery     (a=b=dst)
  kind 10+op        = external op applied (a, b = op args)
Host-side lowering lives in demi_tpu/device/encoding.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..dsl import DSLApp
from . import ops

# External-op codes (device program encoding of ExternalEvents;
# closure-form WaitCondition and CodeBlock are host-tier-only — the
# cond_id WaitCondition form lowers to OP_WAITCOND).
OP_END = 0
OP_START = 1
OP_KILL = 2
OP_SEND = 3
OP_WAIT = 4
OP_PARTITION = 5
OP_UNPARTITION = 6
OP_HARDKILL = 7
# Wait until app condition `a` holds (DSLApp.conditions[a]), with optional
# delivery budget `b` — the device-lowerable WaitCondition form.
OP_WAITCOND = 8

# Record kinds.
REC_NONE = 0
REC_DELIVERY = 1
REC_TIMER = 2
# Wildcard delivery (replay input only): a=dst, b=policy (0=first/FIFO,
# 1=last), msg[0]=class tag. Lowered from WildCardMatch expected events.
REC_WILDCARD = 4
REC_EXT_BASE = 10  # REC_EXT_BASE + op

# Lane status.
ST_DISPATCH = 0
ST_INJECT = 1
ST_DONE = 2
ST_VIOLATION = 3
ST_OVERFLOW = 4


@dataclasses.dataclass(frozen=True)
class DeviceConfig:
    """Static shapes/capacities for the device kernels."""

    num_actors: int
    state_width: int
    msg_width: int
    max_outbox: int
    pool_capacity: int = 256
    max_external_ops: int = 64
    max_steps: int = 512
    invariant_interval: int = 0  # 0 = only at completion
    record_trace: bool = False
    # Track causal parents in trace records (device DPOR): each delivery
    # record carries the trace index of the delivery/injection that created
    # its message. Requires record_trace.
    record_parents: bool = False
    # Probability weight of picking a pending timer vs a message (host
    # counterpart: FullyRandom.timer_weight). 1.0 = uniform over all.
    timer_weight: float = 1.0
    # Early exit: drive the step loop with lax.while_loop instead of a
    # fixed-length scan, so wall-clock tracks the slowest LIVE lane in the
    # batch rather than max_steps. ~10x on workloads whose lanes finish
    # well under the cap (short minimization candidates, early-quiescing
    # sweeps); ~9% loop overhead when every lane runs the full budget —
    # hence opt-in.
    early_exit: bool = False
    # Dynamic-index strategy for the kernels (see device/ops.py): 'auto'
    # uses one-hot compare+where on TPU (vmapped scatters serialize there)
    # and native gathers/scatters elsewhere; 'onehot'/'scatter' force.
    index_mode: str = "auto"
    # SrcDstFIFO randomization (reference: RandomScheduler.scala:702-909,
    # host twin schedulers/random.py SrcDstFIFO): per-(src,dst) channels
    # are TCP-ordered — only each channel's FIFO head is a delivery
    # candidate; timers stay individually choosable. Costs an O(P^2)
    # same-channel compare per step, so opt-in.
    srcdst_fifo: bool = False
    # Batched-replay peek (device twin of STSScheduler.allow_peek /
    # IntervalPeekScheduler): when an expected delivery has no pending
    # match, deliver up to this many pending entries FIFO trying to
    # ENABLE it, keeping the prefix on success and rolling the lane back
    # wholesale on failure. 0 = ignore-absent only. Costs a second
    # in-flight state copy per lane while replaying, so opt-in.
    replay_peek: int = 0
    # Synchronous-round dispatch (device-only exploration mode, no host
    # counterpart): each dispatch step selects ONE uniformly-random
    # deliverable entry PER RECEIVER and delivers them all, with effects
    # computed sequential-equivalently to the ascending-receiver-id
    # linearization (deliveries at distinct receivers commute in this
    # actor model — a handler reads/writes only its own state row). Cuts
    # step count for flood workloads (BASELINE config 5) by up to
    # num_actors x; per-receiver delivery ORDER stays fully randomized,
    # which is what the reachable state space depends on. Segment
    # conditions/invariant intervals are evaluated at round (not
    # delivery) granularity; recorded traces are the canonical
    # linearization and replay sequentially (tests/test_rounds.py pins
    # ignored_absent == 0 through the replay kernel).
    round_delivery: bool = False
    # Trace-row capacity when record_trace is on (None = max_steps). The
    # sequential kernels append at most one record per step, so max_steps
    # rows always suffice; round_delivery appends up to num_actors records
    # per step — size this to the expected delivery total there.
    trace_capacity: Optional[int] = None
    # Message-payload storage dtype for the pool/timer-memory columns
    # ('int32' or 'int16'). The [P, W] pool_msg array dominates the
    # per-lane carry, so halving it halves the HBM traffic of the XLA
    # step loop. Handlers always see int32 (cast at the boundary);
    # requires every app message field to fit the narrow range — the
    # app's contract, unchecked on device.
    msg_dtype: str = "int32"
    # Testing-only escape hatch: force the O(P^2) head recompute even in
    # sequential srcdst_fifo kernels (parity pin for the incremental
    # maintenance; tests/test_device_srcdst.py).
    head_recompute: bool = False
    # Bit-packed boolean gathers on the one-hot path: the network/
    # liveness tests in deliverable_mask pack their bool tables into
    # uint32 words, cutting the one-hot compare cost by ~32x (the cut-
    # matrix gather is O(P*N^2) unpacked — 18.9M ops/step at the
    # config-5 shape). Opt-in TPU lever (bit-identical; parity-pinned in
    # tests/test_device.py; ranked by bench_matrix): the shift/mask ops
    # are XLA-validated but their Mosaic lowering is not, so the pallas
    # backends reject it.
    packed_gathers: bool = False

    def __post_init__(self):
        if self.index_mode not in ("auto", "onehot", "scatter"):
            raise ValueError(
                f"index_mode must be 'auto', 'onehot' or 'scatter', "
                f"got {self.index_mode!r}"
            )
        if self.msg_dtype not in ("int32", "int16"):
            raise ValueError(
                f"msg_dtype must be 'int32' or 'int16', got {self.msg_dtype!r}"
            )
        if self.packed_gathers and self.index_mode == "scatter":
            raise ValueError(
                "packed_gathers applies to the one-hot path; "
                "index_mode='scatter' would silently ignore it"
            )
        if self.round_delivery and self.record_trace and not self.trace_capacity:
            # Round mode appends up to num_actors records per step; the
            # max_steps fallback that suits the sequential kernels would
            # silently truncate the lift (runtime overflow flags lanes,
            # but an undersized default is a config error — fail fast).
            raise ValueError(
                "round_delivery with record_trace requires an explicit "
                "trace_capacity (expected total deliveries + externals)"
            )

    @property
    def msg_jnp_dtype(self):
        return jnp.int16 if self.msg_dtype == "int16" else jnp.int32

    @property
    def use_onehot(self) -> bool:
        if self.index_mode == "auto":
            return jax.default_backend() == "tpu"
        return self.index_mode == "onehot"

    @property
    def track_fifo_heads(self) -> bool:
        """Incremental per-channel FIFO-head maintenance: srcdst_fifo's
        head test drops from an O(P^2) same-channel compare per step to
        O(K*P) at insert + O(P) at consume. The round kernel recomputes
        per ROUND instead (amortized over up to N deliveries), so only
        the sequential kernels carry the extra state."""
        return self.srcdst_fifo and not self.round_delivery and not (
            self.head_recompute
        )

    @property
    def trace_rows(self) -> int:
        return self.trace_capacity if self.trace_capacity else self.max_steps

    @property
    def rec_width(self) -> int:
        # record_parents appends TWO happens-before columns: `parent`
        # (trace index of the record that created this message — the
        # creation edge) and `prev` (trace index of the previous delivery
        # at the same receiver — the program-order edge). Both -1 if none.
        return 3 + self.msg_width + (2 if self.record_parents else 0)

    @staticmethod
    def for_app(app: DSLApp, **overrides) -> "DeviceConfig":
        defaults = dict(
            num_actors=app.num_actors,
            state_width=app.state_width,
            msg_width=app.msg_width,
            max_outbox=app.max_outbox,
        )
        defaults.update(overrides)
        return DeviceConfig(**defaults)


class ScheduleState(NamedTuple):
    """Complete state of one schedule (one lane). All arrays, fixed shapes."""

    actor_state: jnp.ndarray  # [N, S] int32
    started: jnp.ndarray  # [N] bool
    isolated: jnp.ndarray  # [N] bool (Kill = isolation)
    stopped: jnp.ndarray  # [N] bool (HardKill)
    cut: jnp.ndarray  # [N, N] bool, symmetric partition matrix
    # Pending pool.
    pool_valid: jnp.ndarray  # [P] bool
    pool_src: jnp.ndarray  # [P] int32 (num_actors = EXTERNAL)
    pool_dst: jnp.ndarray  # [P] int32
    pool_timer: jnp.ndarray  # [P] bool
    pool_parked: jnp.ndarray  # [P] bool (timer loop-avoidance)
    pool_msg: jnp.ndarray  # [P, W] int32
    pool_seq: jnp.ndarray  # [P] int32 arrival order (FIFO matching)
    pool_crec: jnp.ndarray  # [P] int32 trace index of the creating event (-1 none)
    # Per-channel FIFO-head bits ([0] unless cfg.track_fifo_heads):
    # True iff this entry is its (src,dst) channel's earliest-arrival
    # valid non-timer entry. Maintained incrementally by
    # insert_rows/delivery_effects/purges.
    pool_head: jnp.ndarray  # [P] bool (or [0])
    # Timer-parking memory (host: justScheduledTimers keyed (rcv, fp);
    # device: one remembered timer per actor).
    timer_mem: jnp.ndarray  # [N, W] int32
    timer_mem_valid: jnp.ndarray  # [N] bool
    # Per-actor trace index of the last delivery processed by that actor
    # (-1 none): the program-order HB link recorded alongside pool_crec's
    # creation link when record_parents is on.
    last_rec: jnp.ndarray  # [N] int32
    # Program + bookkeeping.
    ext_cursor: jnp.ndarray  # int32: next external op
    seq_counter: jnp.ndarray  # int32
    deliveries: jnp.ndarray  # int32
    # Bounded-quiescence segment tracking (WaitQuiescence budgets):
    seg_budget: jnp.ndarray  # int32, 0 = unlimited
    seg_start: jnp.ndarray  # int32: deliveries when the segment began
    final_seg: jnp.ndarray  # bool: this dispatch segment is the program's last
    # Condition id gating this dispatch segment (-1 = plain quiescence
    # wait): the WaitCondition twin — the segment also ends once
    # app.conditions[seg_cond](states, alive) holds.
    seg_cond: jnp.ndarray  # int32
    status: jnp.ndarray  # int32 (ST_*)
    violation: jnp.ndarray  # int32 fingerprint (0 = none)
    # Rolling FNV-style fold of every delivered (src, dst, timer?, payload):
    # two lanes share sched_hash iff they delivered the same sequence (modulo
    # 32-bit collisions), making "unique schedules explored" measurable
    # without trace recording (BASELINE.json metric name).
    sched_hash: jnp.ndarray  # uint32
    rng: jnp.ndarray  # PRNG key
    # Optional trace recording.
    trace: jnp.ndarray  # [T, rec_width] int32 (or [0,0] when disabled)
    trace_len: jnp.ndarray  # int32


def init_state(app: DSLApp, cfg: DeviceConfig, key) -> ScheduleState:
    n, s, w, p = cfg.num_actors, cfg.state_width, cfg.msg_width, cfg.pool_capacity
    init_states = np.stack(
        [np.asarray(app.init_state(i), np.int32) for i in range(n)]
    )
    trace_shape = (cfg.trace_rows, cfg.rec_width) if cfg.record_trace else (0, 0)
    return ScheduleState(
        actor_state=jnp.asarray(init_states),
        started=jnp.zeros(n, bool),
        isolated=jnp.zeros(n, bool),
        stopped=jnp.zeros(n, bool),
        cut=jnp.zeros((n, n), bool),
        pool_valid=jnp.zeros(p, bool),
        pool_src=jnp.zeros(p, jnp.int32),
        pool_dst=jnp.zeros(p, jnp.int32),
        pool_timer=jnp.zeros(p, bool),
        pool_parked=jnp.zeros(p, bool),
        pool_msg=jnp.zeros((p, w), cfg.msg_jnp_dtype),
        pool_seq=jnp.zeros(p, jnp.int32),
        pool_crec=jnp.full(p, -1, jnp.int32),
        pool_head=jnp.zeros(p if cfg.track_fifo_heads else 0, bool),
        timer_mem=jnp.zeros((n, w), cfg.msg_jnp_dtype),
        timer_mem_valid=jnp.zeros(n, bool),
        last_rec=jnp.full(n, -1, jnp.int32),
        ext_cursor=jnp.int32(0),
        seq_counter=jnp.int32(0),
        deliveries=jnp.int32(0),
        seg_budget=jnp.int32(0),
        seg_start=jnp.int32(0),
        final_seg=jnp.bool_(False),
        seg_cond=jnp.int32(-1),
        status=jnp.int32(ST_INJECT),
        violation=jnp.int32(0),
        sched_hash=jnp.uint32(0x811C9DC5),  # FNV-1a offset basis
        rng=key,
        trace=jnp.zeros(trace_shape, jnp.int32),
        trace_len=jnp.int32(0),
    )


# ---------------------------------------------------------------------------
# Masks
# ---------------------------------------------------------------------------

def deliverable_mask(state: ScheduleState, cfg: DeviceConfig) -> jnp.ndarray:
    """Which pool entries could be delivered right now. Mirrors the host
    ControlledActorSystem.deliverable predicate exactly."""
    n = cfg.num_actors
    oh = cfg.use_onehot
    dst = state.pool_dst
    src = state.pool_src
    src_is_external = src >= n
    src_clamped = jnp.minimum(src, n - 1)
    if cfg.packed_gathers and not oh:
        # Loud at trace time: 'auto' resolved to the scatter path, so
        # the flag would silently measure nothing.
        raise ValueError(
            "packed_gathers requires one-hot mode; on this backend "
            "index_mode='auto' resolves to scatter — set "
            "index_mode='onehot' explicitly"
        )
    if oh and cfg.packed_gathers:
        dst_ok = ops.packed_gather_bool(state.started, dst) & ~(
            ops.packed_gather_bool(state.stopped, dst)
        )
        dst_reachable = ~ops.packed_gather_bool(state.isolated, dst)
        link_cut = ops.packed_gather_mat(
            state.cut, src_clamped, dst
        ) | ops.packed_gather_bool(state.isolated, src_clamped)
    else:
        dst_ok = ops.gather_vec(state.started, dst, oh) & ~ops.gather_vec(
            state.stopped, dst, oh
        )
        dst_reachable = ~ops.gather_vec(state.isolated, dst, oh)
        link_cut = ops.gather_mat(
            state.cut, src_clamped, dst, oh
        ) | ops.gather_vec(state.isolated, src_clamped, oh)
    # timers/externals only need the receiver un-isolated; internal messages
    # must not cross a partition (either endpoint isolated or link cut).
    passes_network = jnp.where(
        state.pool_timer | src_is_external, True, ~link_cut
    ) & dst_reachable
    return state.pool_valid & ~state.pool_parked & dst_ok & passes_network


def fifo_head_mask(state: ScheduleState, cfg: "DeviceConfig") -> jnp.ndarray:
    """Entries that are their (src,dst) channel's FIFO head (earliest
    arrival seq among valid non-timer entries of the same pair). Timers are
    not channelized and pass through unconditionally.

    With cfg.track_fifo_heads the bits are maintained incrementally
    (insert_rows/delivery_effects/purges) and this is O(P); otherwise
    (round kernel, parity pin) the O(P^2) same-channel recompute runs."""
    if cfg.track_fifo_heads:
        return state.pool_timer | state.pool_head
    return state.pool_timer | recompute_fifo_heads(state)


def recompute_fifo_heads(state: ScheduleState) -> jnp.ndarray:
    """[P] bool: non-timer channel heads, recomputed from scratch."""
    chan = state.pool_valid & ~state.pool_timer
    same_pair = (
        (state.pool_src[:, None] == state.pool_src[None, :])
        & (state.pool_dst[:, None] == state.pool_dst[None, :])
        & chan[:, None]
        & chan[None, :]
    )
    earlier = same_pair & (state.pool_seq[None, :] < state.pool_seq[:, None])
    return chan & ~jnp.any(earlier, axis=1)


def alive_mask(state: ScheduleState) -> jnp.ndarray:
    """Actors the invariant should consider (started, not isolated/stopped;
    host: checkpoint replies None for crashed/isolated actors)."""
    return state.started & ~state.isolated & ~state.stopped


# ---------------------------------------------------------------------------
# Pool maintenance
# ---------------------------------------------------------------------------

def insert_rows(
    state: ScheduleState,
    cfg: DeviceConfig,
    row_valid: jnp.ndarray,  # [K] bool
    row_src: jnp.ndarray,  # [K] int32
    row_dst: jnp.ndarray,  # [K] int32
    row_timer: jnp.ndarray,  # [K] bool
    row_parked: jnp.ndarray,  # [K] bool
    row_msg: jnp.ndarray,  # [K, W] int32
    crec=None,  # int32 trace index of the creating event: scalar or [K]
) -> ScheduleState:
    """Scatter up to K new entries into free pool slots. Overflow (more valid
    rows than free slots) flips the lane status to ST_OVERFLOW."""
    # Proposals carry int32 payloads; storage may be narrower (msg_dtype).
    row_msg = row_msg.astype(state.pool_msg.dtype)
    free = ~state.pool_valid
    # rank among free slots: 1-indexed prefix count
    prefix = ops.prefix_sum(free.astype(jnp.int32), cfg.use_onehot)
    want = ops.prefix_sum(
        row_valid.astype(jnp.int32), cfg.use_onehot
    )  # i-th valid row wants want[i]-th free slot
    # slot index for each row: first index where prefix == want[i] and free
    slots = ops.rank_slots(prefix, want, cfg.use_onehot)  # [K]
    # Totals as reductions, not prefix[-1]/want[-1] reads: trailing-element
    # gathers have no Mosaic lowering (bit-identical either way).
    n_free = jnp.sum(free.astype(jnp.int32))
    n_rows = jnp.sum(row_valid.astype(jnp.int32))
    overflow = jnp.any(row_valid & (want > n_free))
    ok = row_valid & (want <= n_free)

    seqs = state.seq_counter + want  # arrival order follows row order
    k = row_valid.shape[0]
    if cfg.track_fifo_heads:
        # A new row heads its channel iff the pool holds no valid
        # non-timer same-channel entry and no EARLIER row of this batch
        # opens the channel first (batch order = arrival order).
        chan_pool = state.pool_valid & ~state.pool_timer
        exists_pool = jnp.any(
            (row_src[:, None] == state.pool_src[None, :])
            & (row_dst[:, None] == state.pool_dst[None, :])
            & chan_pool[None, :],
            axis=1,
        )
        kidx = jnp.arange(k)
        prior_batch = jnp.any(
            (row_src[:, None] == row_src[None, :])
            & (row_dst[:, None] == row_dst[None, :])
            & (kidx[None, :] < kidx[:, None])
            & (ok & ~row_timer)[None, :],
            axis=1,
        )
        row_head = ok & ~row_timer & ~exists_pool & ~prior_batch
    if cfg.use_onehot:
        oh_kp = ok[:, None] & (
            slots[:, None] == jnp.arange(cfg.pool_capacity)[None, :]
        )  # [K, P] — at most one True per column (slots strictly increase)
        hit = jnp.any(oh_kp, axis=0)
        new_head = (
            ops.scatter_vec_bool(state.pool_head, oh_kp, row_head)
            if cfg.track_fifo_heads
            else state.pool_head
        )
        new_state = state._replace(
            pool_head=new_head,
            pool_valid=state.pool_valid | hit,
            pool_src=ops.scatter_vec_int(state.pool_src, oh_kp, row_src),
            pool_dst=ops.scatter_vec_int(state.pool_dst, oh_kp, row_dst),
            pool_timer=ops.scatter_vec_bool(state.pool_timer, oh_kp, row_timer),
            pool_parked=ops.scatter_vec_bool(
                state.pool_parked, oh_kp, row_parked
            ),
            pool_msg=ops.scatter_rows_int(state.pool_msg, oh_kp, row_msg),
            pool_seq=ops.scatter_vec_int(state.pool_seq, oh_kp, seqs),
            seq_counter=state.seq_counter + n_rows,
            status=jnp.where(overflow, jnp.int32(ST_OVERFLOW), state.status),
        )
        if crec is not None:
            crec = jnp.asarray(crec, jnp.int32)
            if crec.ndim == 0:
                new_crec = jnp.where(hit, crec, state.pool_crec)
            else:  # per-row creator links ([K], round-delivery inserts)
                new_crec = jnp.where(
                    hit,
                    jnp.sum(
                        jnp.where(oh_kp, crec[:, None], 0), axis=0
                    ),
                    state.pool_crec,
                )
            new_state = new_state._replace(pool_crec=new_crec)
        return new_state
    slots = jnp.where(ok, slots, cfg.pool_capacity)  # out-of-range => dropped
    new_state = state._replace(
        pool_head=(
            state.pool_head.at[slots].set(row_head, mode="drop")
            if cfg.track_fifo_heads
            else state.pool_head
        ),
        pool_valid=state.pool_valid.at[slots].set(True, mode="drop"),
        pool_src=state.pool_src.at[slots].set(row_src, mode="drop"),
        pool_dst=state.pool_dst.at[slots].set(row_dst, mode="drop"),
        pool_timer=state.pool_timer.at[slots].set(row_timer, mode="drop"),
        pool_parked=state.pool_parked.at[slots].set(row_parked, mode="drop"),
        pool_msg=state.pool_msg.at[slots].set(row_msg, mode="drop"),
        pool_seq=state.pool_seq.at[slots].set(seqs, mode="drop"),
        seq_counter=state.seq_counter + n_rows,
        status=jnp.where(overflow, jnp.int32(ST_OVERFLOW), state.status),
    )
    if crec is not None:
        # Creator links are only maintained when tracing (DPOR mode) —
        # untraced sweeps skip the extra scatter entirely.
        crec = jnp.asarray(crec, jnp.int32)
        new_state = new_state._replace(
            pool_crec=state.pool_crec.at[slots].set(
                jnp.broadcast_to(crec, (k,)), mode="drop"
            )
        )
    return new_state


# ---------------------------------------------------------------------------
# Delivery
# ---------------------------------------------------------------------------

class RowProposal(NamedTuple):
    """Pool-insert rows proposed by one effects pass (the insert itself is
    deferred so the fused step pays ONE insert for both step kinds)."""

    valid: jnp.ndarray  # [K] bool
    src: jnp.ndarray  # [K] int32
    dst: jnp.ndarray  # [K] int32
    timer: jnp.ndarray  # [K] bool
    parked: jnp.ndarray  # [K] bool
    msg: jnp.ndarray  # [K, W] int32

    @staticmethod
    def concat(a: "RowProposal", b: "RowProposal") -> "RowProposal":
        return RowProposal(
            *(jnp.concatenate([x, y]) for x, y in zip(a, b))
        )


def delivery_effects(
    state: ScheduleState, cfg: DeviceConfig, app: DSLApp, idx: jnp.ndarray
) -> Tuple[ScheduleState, RowProposal, jnp.ndarray]:
    """Deliver pool entry ``idx`` minus the pool insert: run the app handler
    for the receiver, consume the entry, update timer parking; return the
    outbox as a RowProposal plus the trace record for this delivery.

    ``idx`` must point at a deliverable entry; an invalid index
    (== pool_capacity) makes the whole pass a no-op."""
    n = cfg.num_actors
    oh = cfg.use_onehot
    valid_idx = idx < cfg.pool_capacity
    safe_idx = jnp.minimum(idx, cfg.pool_capacity - 1)
    src = ops.get_scalar(state.pool_src, safe_idx, oh)
    dst = ops.get_scalar(state.pool_dst, safe_idx, oh)
    # Handlers (and trace records) always see int32 payloads regardless
    # of the pool's storage dtype.
    msg = ops.get_row(state.pool_msg, safe_idx, oh).astype(jnp.int32)
    is_timer = ops.get_scalar(state.pool_timer, safe_idx, oh)
    parent_rec = ops.get_scalar(state.pool_crec, safe_idx, oh)

    handler_state = ops.get_row(state.actor_state, dst, oh)
    new_row, outbox = app.handler(dst, handler_state, src, msg)
    # outbox: [K, 2+W] (valid, dst, msg...)
    k = outbox.shape[0]
    ob_valid = (outbox[:, 0] != 0) & valid_idx
    ob_dst = jnp.clip(outbox[:, 1], 0, n - 1)
    ob_msg = outbox[:, 2:]
    ob_src = jnp.full((k,), 0, jnp.int32) + dst
    # Timer classification: self-send with a timer tag.
    if app.timer_tags:
        tags = jnp.asarray(list(app.timer_tags), jnp.int32)
        is_timer_tag = jnp.any(ob_msg[:, 0:1] == tags[None, :], axis=1)
    else:
        is_timer_tag = jnp.zeros(k, bool)
    ob_timer = is_timer_tag & (ob_dst == dst)
    # Park re-armed copies of the remembered timer (loop avoidance).
    mem_match = jnp.all(
        ob_msg == ops.gather_rows(state.timer_mem, ob_dst, oh), axis=1
    ) & ops.gather_vec(state.timer_mem_valid, ob_dst, oh)
    ob_parked = ob_timer & mem_match

    # Apply handler effects only when the delivery really happened.
    new_actor_state = ops.set_row(
        state.actor_state, dst, new_row, valid_idx, oh
    )
    # Fold this delivery into the lane's schedule fingerprint (uint32
    # FNV-style: multiply by an odd prime, mix in src/dst/timer/payload).
    # Wraparound is the modulus; identical delivered sequences hash equal.
    w = msg.shape[0]
    pw = jnp.asarray(
        [pow(31, j, 1 << 32) for j in range(w)], jnp.uint32
    )
    mix = (
        jnp.sum(msg.astype(jnp.uint32) * pw)
        + src.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
        + dst.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
        + is_timer.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
    )
    folded = state.sched_hash * jnp.uint32(0x01000193) + mix
    # Consume the entry.
    state = state._replace(
        actor_state=new_actor_state,
        pool_valid=ops.set_scalar(
            state.pool_valid, safe_idx, False, valid_idx, oh
        ),
        deliveries=state.deliveries + valid_idx.astype(jnp.int32),
        sched_hash=jnp.where(valid_idx, folded, state.sched_hash),
    )
    if cfg.track_fifo_heads:
        # Promote the consumed channel's successor: recompute head bits
        # for THIS channel only (O(P); the consumed entry may not have
        # been the head — replay delivers by content — so a plain
        # min-seq recompute over the channel is the exact rule).
        upd = valid_idx & ~is_timer
        samech = (
            (state.pool_src == src)
            & (state.pool_dst == dst)
            & state.pool_valid
            & ~state.pool_timer
        )
        seqs = jnp.where(samech, state.pool_seq, jnp.int32(2**30))
        new_head = samech & (state.pool_seq == jnp.min(seqs))
        pool_head = jnp.where(samech & upd, new_head, state.pool_head)
        pool_head = ops.set_scalar(pool_head, safe_idx, False, valid_idx, oh)
        state = state._replace(pool_head=pool_head)

    # Timer memory update: delivering a timer remembers it; delivering a
    # non-timer clears all memory and unparks everything (host semantics:
    # justScheduledTimers cleared + timersToResend flushed on non-timer
    # delivery, RandomScheduler.scala:100-117).
    delivered_timer = is_timer & valid_idx
    cleared = valid_idx & ~is_timer
    timer_mem = jnp.where(
        cleared,
        jnp.zeros_like(state.timer_mem),
        ops.set_row(
            state.timer_mem, dst, msg.astype(state.timer_mem.dtype),
            delivered_timer, oh,
        ),
    )
    timer_mem_valid = jnp.where(
        cleared,
        jnp.zeros_like(state.timer_mem_valid),
        ops.set_scalar(state.timer_mem_valid, dst, True, delivered_timer, oh),
    )
    pool_parked = jnp.where(
        valid_idx & ~is_timer, jnp.zeros_like(state.pool_parked), state.pool_parked
    )
    state = state._replace(
        timer_mem=timer_mem, timer_mem_valid=timer_mem_valid, pool_parked=pool_parked
    )

    rows = RowProposal(ob_valid, ob_src, ob_dst, ob_timer, ob_parked, ob_msg)
    if cfg.record_trace:
        kind = jnp.where(is_timer, REC_TIMER, REC_DELIVERY)
        parts = [jnp.stack([kind, src, dst]), msg]
        if cfg.record_parents:
            # Two HB columns: creation link (pool_crec) + program-order
            # link (previous delivery at this receiver). This record will
            # land at trace index state.trace_len, which also becomes the
            # receiver's new last_rec.
            prev_rec = ops.get_scalar(state.last_rec, dst, oh)
            parts.append(parent_rec[None])
            parts.append(prev_rec[None])
            state = state._replace(
                last_rec=ops.set_scalar(
                    state.last_rec, dst, state.trace_len, valid_idx, oh
                )
            )
        rec = jnp.concatenate(parts)
    else:
        rec = jnp.zeros((0,), jnp.int32)
    return state, rows, rec


def deliver_index(
    state: ScheduleState, cfg: DeviceConfig, app: DSLApp, idx: jnp.ndarray
) -> ScheduleState:
    """Deliver pool entry ``idx``: delivery_effects + the pool insert +
    trace append (the standalone form used by the replay/DPOR kernels)."""
    valid_idx = idx < cfg.pool_capacity
    rec_idx = state.trace_len  # this delivery's record position
    state, rows, rec = delivery_effects(state, cfg, app, idx)
    state = insert_rows(
        state, cfg, rows.valid, rows.src, rows.dst, rows.timer, rows.parked,
        rows.msg, crec=rec_idx if cfg.record_parents else None,
    )
    if cfg.record_trace:
        state = _append_record(state, cfg, rec, valid_idx)
    return state


def _append_record(state: ScheduleState, cfg: DeviceConfig, rec, enabled) -> ScheduleState:
    pos = jnp.minimum(state.trace_len, cfg.trace_rows - 1)
    new_trace = ops.set_row(state.trace, pos, rec, enabled, cfg.use_onehot)
    return state._replace(
        trace=new_trace, trace_len=state.trace_len + enabled.astype(jnp.int32)
    )


# ---------------------------------------------------------------------------
# External-op injection
# ---------------------------------------------------------------------------

def external_effects(
    state: ScheduleState,
    cfg: DeviceConfig,
    app: DSLApp,
    initial_rows: jnp.ndarray,  # [N, K0, 2+W] precomputed initial_msgs per actor
    init_states: jnp.ndarray,  # [N, S]
    op: jnp.ndarray,
    a: jnp.ndarray,
    b: jnp.ndarray,
    msg: jnp.ndarray,  # [W]
) -> Tuple[ScheduleState, RowProposal, jnp.ndarray, jnp.ndarray]:
    """Apply one external op (Start/Kill/Send/Partition/...) minus the pool
    insert; mirrors BaseScheduler._inject_one. Returns the proposed rows
    (Start's initial messages + Send's external message), the trace record,
    and its enabled flag. Pass OP_END to make the whole pass a no-op."""
    n = cfg.num_actors
    oh = cfg.use_onehot
    a_c = jnp.clip(a, 0, n - 1)
    b_c = jnp.clip(b, 0, n - 1)

    is_start = op == OP_START
    is_kill = op == OP_KILL
    is_hardkill = op == OP_HARDKILL
    is_send = op == OP_SEND
    is_partition = op == OP_PARTITION
    is_unpartition = op == OP_UNPARTITION

    was_started = ops.get_scalar(state.started, a_c, oh)
    was_stopped = ops.get_scalar(state.stopped, a_c, oh)
    # Fresh start = first Start or restart after HardKill; a Start for a
    # merely isolated actor is recovery (un-isolate, keep state, no re-emit)
    # — host semantics: ControlledActorSystem.spawn.
    fresh_start = is_start & (~was_started | was_stopped)
    # Start: begin (or recover) actor a.
    started = ops.set_scalar(state.started, a_c, True, is_start, oh)
    isolated = ops.set_scalar(
        state.isolated, a_c, is_kill, is_start | is_kill, oh
    )
    stopped = ops.set_scalar(
        state.stopped, a_c, is_hardkill, is_start | is_hardkill, oh
    )
    # Start after HardKill resets app state.
    actor_state = ops.set_row(
        state.actor_state, a_c, ops.get_row(init_states, a_c, oh),
        fresh_start, oh,
    )
    if oh:
        oh_a = ops.onehot(a_c, n)
        oh_b = ops.onehot(b_c, n)
        sym = (oh_a[:, None] & oh_b[None, :]) | (oh_b[:, None] & oh_a[None, :])
        cut = jnp.where(
            sym & (is_partition | is_unpartition), is_partition, state.cut
        )
    else:
        cut_val = jnp.where(
            is_partition,
            True,
            jnp.where(is_unpartition, False, state.cut[a_c, b_c]),
        )
        cut = state.cut.at[a_c, b_c].set(cut_val)
        cut = cut.at[b_c, a_c].set(cut_val)

    # HardKill scrub, branchless (the fused step can't afford a lax.cond
    # whose both sides run under vmap anyway).
    touch = ((state.pool_src == a_c) | (state.pool_dst == a_c)) & is_hardkill
    state = state._replace(
        started=started, isolated=isolated, stopped=stopped,
        actor_state=actor_state, cut=cut,
        pool_valid=state.pool_valid & ~touch,
        pool_head=(
            state.pool_head & ~touch
            if state.pool_head.shape[0]
            else state.pool_head
        ),
    )

    # Proposed rows: the Start's initial messages (fresh-start only) and the
    # Send's external message, as one [K0+1]-row proposal.
    k0 = initial_rows.shape[1]
    if k0 > 0:
        rows = ops.get_row(
            initial_rows.reshape(n, -1), a_c, oh
        ).reshape(k0, 2 + cfg.msg_width)
        r_valid = (rows[:, 0] != 0) & fresh_start
        r_dst = jnp.clip(rows[:, 1], 0, n - 1)
        r_msg = rows[:, 2:]
        if app.timer_tags:
            tags = jnp.asarray(list(app.timer_tags), jnp.int32)
            r_timer = jnp.any(r_msg[:, 0:1] == tags[None, :], axis=1) & (r_dst == a_c)
        else:
            r_timer = jnp.zeros(k0, bool)
        proposal = RowProposal(
            valid=jnp.concatenate([r_valid, is_send[None]]),
            src=jnp.concatenate([jnp.full((k0,), a_c), jnp.asarray([n], jnp.int32)]),
            dst=jnp.concatenate([r_dst, a_c[None]]),
            timer=jnp.concatenate([r_timer, jnp.asarray([False])]),
            parked=jnp.zeros(k0 + 1, bool),
            msg=jnp.concatenate([r_msg, msg[None, :]]),
        )
    else:
        proposal = RowProposal(
            valid=is_send[None],
            src=jnp.asarray([n], jnp.int32),  # EXTERNAL sender id
            dst=a_c[None],
            timer=jnp.asarray([False]),
            parked=jnp.asarray([False]),
            msg=msg[None, :],
        )

    if cfg.record_trace:
        parts = [jnp.stack([REC_EXT_BASE + op, a, b]), msg]
        if cfg.record_parents:
            # External injections have neither creation nor program-order
            # predecessors (both HB columns -1).
            parts.append(jnp.asarray([-1, -1], jnp.int32))
        rec = jnp.concatenate(parts)
    else:
        rec = jnp.zeros((0,), jnp.int32)
    enabled = (op != OP_END) & (op != OP_WAIT) & (op != OP_WAITCOND)
    return state, proposal, rec, enabled


def apply_external_op(
    state: ScheduleState,
    cfg: DeviceConfig,
    app: DSLApp,
    initial_rows: jnp.ndarray,
    init_states: jnp.ndarray,
    op: jnp.ndarray,
    a: jnp.ndarray,
    b: jnp.ndarray,
    msg: jnp.ndarray,
) -> ScheduleState:
    """external_effects + the pool insert + trace append (the standalone
    form used by the replay/DPOR kernels)."""
    rec_idx = state.trace_len  # this op's record position (creator link)
    state, rows, rec, enabled = external_effects(
        state, cfg, app, initial_rows, init_states, op, a, b, msg
    )
    state = insert_rows(
        state, cfg, rows.valid, rows.src, rows.dst, rows.timer, rows.parked,
        rows.msg, crec=rec_idx if cfg.record_parents else None,
    )
    if cfg.record_trace:
        state = _append_record(state, cfg, rec, enabled)
    return state


def check_invariant(
    state: ScheduleState, app: DSLApp
) -> jnp.ndarray:
    return app.invariant(state.actor_state, alive_mask(state))
