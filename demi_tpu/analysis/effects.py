"""Static read/write field-set extraction from handler ASTs.

The commutativity input DPOR wants (Quasi-Optimal POR's independence
relation, arXiv:1802.03950; the event-driven tailoring of
arXiv:2307.15930) is *per (actor-class, message-type)*: which state
fields may a handler read / write when dispatched on each message tag?
Two deliveries to the same actor provably commute when neither's writes
intersect the other's reads-or-writes — with one refinement: fields that
both sides only ever |=-accumulate (monotone bitmask joins like raft's
HEARD discovery mask) commute with each other even though both "write".

Extraction is an abstract interpretation of the handler's Python source:

  - DSL apps (jax-traced handlers): the actual function object's closure
    cells + globals resolve the symbolic state-layout constants (ROLE,
    NEXT = LOG_START + 2 * log_cap, ...), ``jax.lax.switch(tag, branches,
    ...)`` splits the analysis per message tag, and the dual-tier index
    helpers (vget/vset/vgather/seg_set) plus jnp.where/clip/... are
    interpreted over a small domain: integer ranges, state-shaped values
    carrying their accumulated writes, and opaque values carrying the
    fields read to compute them. ``jnp.clip``-bounded dynamic indices
    stay finite ranges, so a log-region gather reads the log region, not
    the whole state vector.
  - host Actor classes: attribute-level effects of ``receive``, split
    per message type when the method body is a top-level dispatch chain
    on ``msg[0] == <const>`` / ``isinstance(msg, T)``.

Unsoundness is impossible by construction: any construct the interpreter
does not understand degrades that component to UNKNOWN, and UNKNOWN
conflicts with everything (unknown => dependent). An analysis that
crashes entirely yields ``AppEffects.unknown()`` — a relation that never
declares anything independent.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from dataclasses import dataclass, field
from typing import (
    Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple
)

#: UNKNOWN field set — conflicts with everything.
UNKNOWN = None

FieldSet = Optional[FrozenSet]  # None = UNKNOWN (all fields)


def fs_union(a: FieldSet, b: FieldSet) -> FieldSet:
    if a is UNKNOWN or b is UNKNOWN:
        return UNKNOWN
    return a | b


def fs_overlap(a: FieldSet, b: FieldSet) -> bool:
    if a is UNKNOWN or b is UNKNOWN:
        return bool(a) if b is UNKNOWN else bool(b) if a is UNKNOWN else True
    return bool(a & b)


@dataclass(frozen=True)
class EffectSet:
    """Per-(handler, message-type) field effects. ``or_writes`` are
    fields ONLY ever written as ``f |= expr`` (with expr not reading f
    beyond that self-term); they commute among themselves."""

    reads: FieldSet = frozenset()
    writes: FieldSet = frozenset()
    or_writes: FrozenSet = frozenset()

    @classmethod
    def unknown(cls) -> "EffectSet":
        return cls(reads=UNKNOWN, writes=UNKNOWN, or_writes=frozenset())

    def is_unknown(self) -> bool:
        return self.reads is UNKNOWN or self.writes is UNKNOWN

    def union(self, other: "EffectSet") -> "EffectSet":
        """Conservative merge of two control-flow branches. A field
        or-written on one path and plainly written on the other must
        degrade to a plain write."""
        plain = fs_union(self.writes, other.writes)
        orw = self.or_writes | other.or_writes
        if plain is not UNKNOWN:
            orw = orw - plain
        else:
            orw = frozenset()
        return EffectSet(
            reads=fs_union(self.reads, other.reads), writes=plain,
            or_writes=orw,
        )

    def to_json(self) -> Dict:
        return {
            "reads": sorted(self.reads) if self.reads is not UNKNOWN else "unknown",
            "writes": sorted(self.writes) if self.writes is not UNKNOWN else "unknown",
            "or_writes": sorted(self.or_writes),
        }


def effects_commute(a: EffectSet, b: EffectSet) -> bool:
    """May deliveries with effects ``a`` and ``b`` to the same actor be
    flipped without changing the reachable state? Sound conservative
    check: plain writes conflict with everything; or-accumulations
    conflict with reads and plain writes but commute with each other."""
    if a.is_unknown() or b.is_unknown():
        return False
    if fs_overlap(a.writes, fs_union(b.reads, fs_union(b.writes, b.or_writes))):
        return False
    if fs_overlap(b.writes, fs_union(a.reads, fs_union(a.writes, a.or_writes))):
        return False
    if fs_overlap(a.or_writes, b.reads) or fs_overlap(b.or_writes, a.reads):
        return False
    return True


# ---------------------------------------------------------------------------
# Abstract values
# ---------------------------------------------------------------------------

class AbsVal:
    reads: FieldSet = frozenset()


@dataclass(frozen=True)
class Rng(AbsVal):
    """Integer in [lo, hi] (inclusive), plus the state fields read to
    compute it."""

    lo: int
    hi: int
    reads: FieldSet = frozenset()

    @property
    def const(self) -> Optional[int]:
        return self.lo if self.lo == self.hi else None


@dataclass(frozen=True)
class Opaque(AbsVal):
    """Any non-state value; ``length`` tracks 1-D vector length when
    statically known (seg_set write extents)."""

    reads: FieldSet = frozenset()
    length: Optional[int] = None


@dataclass(frozen=True)
class Py(AbsVal):
    """A resolved Python constant/object from the closure environment
    (bug-flag strings, layout ints, helper function objects, modules)."""

    value: Any
    reads: FieldSet = frozenset()


@dataclass(frozen=True)
class SVal(AbsVal):
    """A state-shaped value: the original state vector with ``writes``
    possibly modified (``or_writes`` only by |=), computed by reading
    ``reads``."""

    writes: FieldSet = frozenset()
    or_writes: FrozenSet = frozenset()
    reads: FieldSet = frozenset()
    width: Optional[int] = None


@dataclass(frozen=True)
class TupleVal(AbsVal):
    items: Tuple[AbsVal, ...] = ()

    @property
    def reads(self) -> FieldSet:  # type: ignore[override]
        out: FieldSet = frozenset()
        for it in self.items:
            out = fs_union(out, it.reads)
        return out


def _reads_of(v: AbsVal) -> FieldSet:
    return v.reads


def _merge_vals(a: AbsVal, b: AbsVal, extra_reads: FieldSet) -> AbsVal:
    """Control-flow join (jnp.where / unresolved `if`)."""
    if isinstance(a, SVal) and isinstance(b, SVal):
        eff = EffectSet(frozenset(), a.writes, a.or_writes).union(
            EffectSet(frozenset(), b.writes, b.or_writes)
        )
        return SVal(
            writes=eff.writes, or_writes=eff.or_writes,
            reads=fs_union(extra_reads, fs_union(a.reads, b.reads)),
            width=a.width if a.width == b.width else None,
        )
    if isinstance(a, SVal) or isinstance(b, SVal):
        # One side replaces the state wholesale with a non-state value.
        sv = a if isinstance(a, SVal) else b
        other = b if isinstance(a, SVal) else a
        return SVal(
            writes=UNKNOWN, or_writes=frozenset(),
            reads=fs_union(extra_reads, fs_union(sv.reads, other.reads)),
            width=sv.width,
        )
    if isinstance(a, Rng) and isinstance(b, Rng):
        return Rng(
            min(a.lo, b.lo), max(a.hi, b.hi),
            fs_union(extra_reads, fs_union(a.reads, b.reads)),
        )
    la = a.length if isinstance(a, Opaque) else None
    lb = b.length if isinstance(b, Opaque) else None
    return Opaque(
        fs_union(extra_reads, fs_union(_reads_of(a), _reads_of(b))),
        length=la if la == lb else None,
    )


class _Bail(Exception):
    """Abort the whole analysis -> EffectSet.unknown()."""


_MAX_DEPTH = 10
_PURE_ARRAY_FNS = {
    "where", "stack", "concatenate", "sum", "any", "all", "max", "min",
    "maximum", "minimum", "abs", "arange", "reshape", "astype", "clip",
    "full", "zeros", "ones", "zeros_like", "ones_like", "int32", "bool_",
    "asarray", "array", "logical_and", "logical_or", "logical_not",
    "equal", "not_equal", "eye", "argmax", "argmin", "cumsum", "prod",
}


class _Frame:
    def __init__(self, env: Dict[str, Any], depth: int):
        self.locals: Dict[str, AbsVal] = {}
        self.ast_defs: Dict[str, ast.expr] = {}
        self.env = env
        self.depth = depth
        self.returns: List[AbsVal] = []


def _fn_env(fn: Callable) -> Dict[str, Any]:
    env = dict(fn.__globals__)
    code = fn.__code__
    if fn.__closure__:
        env.update(
            {
                name: cell.cell_contents
                for name, cell in zip(code.co_freevars, fn.__closure__)
            }
        )
    return env


def _fn_ast(fn: Callable) -> ast.FunctionDef:
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    node = tree.body[0]
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise _Bail(f"not a function def: {fn!r}")
    return node


class _Interp:
    """The per-function abstract interpreter (see module docstring)."""

    def __init__(self):
        self._stack: List[Callable] = []

    # -- function-level entry ---------------------------------------------
    def run_fn(self, fn: Callable, args: List[AbsVal],
               kw: Optional[Dict[str, AbsVal]] = None) -> AbsVal:
        if len(self._stack) >= _MAX_DEPTH or fn in self._stack:
            raise _Bail("recursion/depth limit")
        node = _fn_ast(fn)
        frame = _Frame(_fn_env(fn), len(self._stack))
        params = [a.arg for a in node.args.posonlyargs + node.args.args]
        kw = dict(kw or {})
        if any(k not in params for k in kw):
            raise _Bail(f"unmatched keyword args calling {fn.__name__}")
        defaults = node.args.defaults
        for i, p in enumerate(params):
            if i < len(args):
                frame.locals[p] = args[i]
            elif p in kw:
                frame.locals[p] = kw[p]
            else:
                # Unfilled default -> evaluate it in the frame (constants
                # like a=0) or degrade to opaque.
                di = i - (len(params) - len(defaults))
                if 0 <= di < len(defaults):
                    frame.locals[p] = self.eval(defaults[di], frame)
                else:
                    frame.locals[p] = Opaque()
        self._stack.append(fn)
        try:
            self.exec_block(node.body, frame)
        finally:
            self._stack.pop()
        if not frame.returns:
            return Opaque()
        out = frame.returns[0]
        for r in frame.returns[1:]:
            out = _merge_vals(out, r, frozenset())
        return out

    # -- statements --------------------------------------------------------
    def exec_block(self, stmts: List[ast.stmt], frame: _Frame) -> None:
        for st in stmts:
            self.exec_stmt(st, frame)

    def exec_stmt(self, st: ast.stmt, frame: _Frame) -> None:
        if isinstance(st, ast.Assign):
            val = self.eval(st.value, frame)
            for tgt in st.targets:
                self._bind(tgt, val, st.value, frame)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._bind(st.target, self.eval(st.value, frame), st.value, frame)
        elif isinstance(st, ast.AugAssign):
            synth = ast.BinOp(left=st.target, op=st.op, right=st.value)
            ast.copy_location(synth, st)
            ast.fix_missing_locations(synth)
            self._bind(st.target, self.eval(synth, frame), synth, frame)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                frame.returns.append(self.eval(st.value, frame))
        elif isinstance(st, ast.If):
            cond = self.eval(st.test, frame)
            if isinstance(cond, Py) and isinstance(cond.value, bool):
                self.exec_block(st.body if cond.value else st.orelse, frame)
                return
            before = dict(frame.locals)
            self.exec_block(st.body, frame)
            after_then = frame.locals
            frame.locals = dict(before)
            self.exec_block(st.orelse, frame)
            merged: Dict[str, AbsVal] = {}
            for name in set(after_then) | set(frame.locals):
                a, b = after_then.get(name), frame.locals.get(name)
                if a is None or b is None:
                    merged[name] = a if a is not None else b  # type: ignore
                else:
                    merged[name] = _merge_vals(a, b, _reads_of(cond))
            frame.locals = merged
        elif isinstance(st, (ast.Expr, ast.Pass)):
            if isinstance(st, ast.Expr):
                self.eval(st.value, frame)
        elif isinstance(st, (ast.For, ast.While)):
            # Loops are outside the modeled subset: a single body pass
            # misses writes through loop-carried index variables
            # (`i = START; for _: vset(state, i, ..); i += 1` would
            # analyze to writes={START} only), and a sound fixed point
            # needs widening this domain doesn't have. Zoo handlers are
            # loop-free jax dataflow; anything else degrades to UNKNOWN.
            raise _Bail("loops are not modeled (unknown => dependent)")
        elif isinstance(st, ast.Assert):
            self.eval(st.test, frame)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs used as values (rare in handlers) — opaque.
            frame.locals[st.name] = Opaque()
        else:
            raise _Bail(f"unsupported statement {type(st).__name__}")

    def _bind(self, tgt: ast.expr, val: AbsVal, src_ast: Optional[ast.expr],
              frame: _Frame) -> None:
        if isinstance(tgt, ast.Name):
            frame.locals[tgt.id] = val
            if src_ast is not None:
                frame.ast_defs[tgt.id] = src_ast
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            items = (
                list(val.items)
                if isinstance(val, TupleVal)
                else [Opaque(_reads_of(val))] * len(tgt.elts)
            )
            if len(items) != len(tgt.elts):
                items = [Opaque(_reads_of(val))] * len(tgt.elts)
            for t, v in zip(tgt.elts, items):
                self._bind(t, v, None, frame)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, Opaque(_reads_of(val)), None, frame)
        else:
            raise _Bail(f"unsupported bind target {type(tgt).__name__}")

    # -- expressions -------------------------------------------------------
    def eval(self, node: ast.expr, frame: _Frame) -> AbsVal:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return Py(node.value)
            if isinstance(node.value, int):
                return Rng(node.value, node.value)
            return Py(node.value)
        if isinstance(node, ast.Name):
            if node.id in frame.locals:
                return frame.locals[node.id]
            if node.id in frame.env:
                return self._lift(frame.env[node.id])
            return Opaque()
        if isinstance(node, ast.Attribute):
            base = self.eval(node.value, frame)
            if isinstance(base, Py):
                try:
                    return self._lift(getattr(base.value, node.attr))
                except AttributeError:
                    return Opaque(base.reads)
            return Opaque(_reads_of(base))
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node, frame)
        if isinstance(node, ast.BinOp):
            return self._eval_binop(node, frame)
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand, frame)
            if isinstance(node.op, ast.USub) and isinstance(v, Rng):
                return Rng(-v.hi, -v.lo, v.reads)
            return Opaque(_reads_of(v))
        if isinstance(node, ast.BoolOp):
            reads: FieldSet = frozenset()
            for sub in node.values:
                reads = fs_union(reads, _reads_of(self.eval(sub, frame)))
            return Opaque(reads)
        if isinstance(node, ast.Compare):
            left = self.eval(node.left, frame)
            rights = [self.eval(c, frame) for c in node.comparators]
            if (
                isinstance(left, Py)
                and len(rights) == 1
                and isinstance(rights[0], Py)
                and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Eq, ast.NotEq, ast.Is, ast.IsNot))
            ):
                eq = left.value == rights[0].value if isinstance(
                    node.ops[0], (ast.Eq, ast.Is)
                ) else left.value != rights[0].value
                return Py(bool(eq))
            reads = _reads_of(left)
            for r in rights:
                reads = fs_union(reads, _reads_of(r))
            return Opaque(reads)
        if isinstance(node, (ast.Tuple, ast.List)):
            return TupleVal(tuple(self.eval(e, frame) for e in node.elts))
        if isinstance(node, ast.Call):
            return self._eval_call(node, frame)
        if isinstance(node, ast.IfExp):
            cond = self.eval(node.test, frame)
            if isinstance(cond, Py) and isinstance(cond.value, bool):
                return self.eval(node.body if cond.value else node.orelse, frame)
            return _merge_vals(
                self.eval(node.body, frame), self.eval(node.orelse, frame),
                _reads_of(cond),
            )
        if isinstance(node, ast.JoinedStr):
            return Opaque()
        if isinstance(node, ast.Lambda):
            return Opaque()
        if isinstance(node, ast.Slice):
            reads: FieldSet = frozenset()
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    reads = fs_union(reads, _reads_of(self.eval(part, frame)))
            return Opaque(reads)
        raise _Bail(f"unsupported expression {type(node).__name__}")

    def _lift(self, value: Any) -> AbsVal:
        if isinstance(value, bool):
            return Py(value)
        if isinstance(value, int):
            return Rng(value, value)
        return Py(value)

    def _eval_subscript(self, node: ast.Subscript, frame: _Frame) -> AbsVal:
        base = self.eval(node.value, frame)
        sl = node.slice
        if isinstance(base, SVal):
            if isinstance(sl, ast.Slice):
                lo = self.eval(sl.lower, frame) if sl.lower else Rng(0, 0)
                hi = self.eval(sl.upper, frame) if sl.upper else None
                if (
                    isinstance(lo, Rng) and lo.const is not None
                    and hi is not None and isinstance(hi, Rng)
                    and hi.const is not None and sl.step is None
                ):
                    fields = frozenset(range(lo.const, hi.const))
                    return Opaque(
                        fs_union(base.reads, fields),
                        length=hi.const - lo.const,
                    )
                return Opaque(UNKNOWN)
            idx = self.eval(sl, frame)
            if isinstance(idx, Rng):
                fields = frozenset(range(idx.lo, idx.hi + 1))
                return Opaque(
                    fs_union(fs_union(base.reads, idx.reads), fields)
                )
            if isinstance(idx, TupleVal) or idx is None:
                return Opaque(UNKNOWN)
            # [None] reshape of a state-derived scalar etc.
            if isinstance(sl, ast.Constant) and sl.value is None:
                return Opaque(base.reads)
            return Opaque(UNKNOWN)
        if isinstance(base, Py):
            idx = self.eval(sl, frame)
            if isinstance(idx, Rng) and idx.const is not None:
                try:
                    return self._lift(base.value[idx.const])
                except Exception:
                    return Opaque(idx.reads)
            return Opaque(fs_union(base.reads, _reads_of(idx)))
        if isinstance(base, TupleVal):
            idx = self.eval(sl, frame)
            if isinstance(idx, Rng) and idx.const is not None and (
                0 <= idx.const < len(base.items)
            ):
                return base.items[idx.const]
            return Opaque(base.reads)
        idx_reads: FieldSet = frozenset()
        if not isinstance(sl, ast.Slice):
            idx_reads = _reads_of(self.eval(sl, frame))
        else:
            for part in (sl.lower, sl.upper, sl.step):
                if part is not None:
                    idx_reads = fs_union(
                        idx_reads, _reads_of(self.eval(part, frame))
                    )
        return Opaque(fs_union(_reads_of(base), idx_reads))

    def _eval_binop(self, node: ast.BinOp, frame: _Frame) -> AbsVal:
        left = self.eval(node.left, frame)
        right = self.eval(node.right, frame)
        if isinstance(left, Rng) and isinstance(right, Rng):
            reads = fs_union(left.reads, right.reads)
            if isinstance(node.op, ast.Add):
                return Rng(left.lo + right.lo, left.hi + right.hi, reads)
            if isinstance(node.op, ast.Sub):
                return Rng(left.lo - right.hi, left.hi - right.lo, reads)
            if isinstance(node.op, ast.Mult):
                corners = [
                    a * b
                    for a in (left.lo, left.hi)
                    for b in (right.lo, right.hi)
                ]
                return Rng(min(corners), max(corners), reads)
        if isinstance(left, Py) and isinstance(right, Py):
            try:
                if isinstance(node.op, ast.Add):
                    return self._lift(left.value + right.value)
                if isinstance(node.op, ast.Mod):
                    return self._lift(left.value % right.value)
            except Exception:
                pass
        return Opaque(fs_union(_reads_of(left), _reads_of(right)))

    # -- calls -------------------------------------------------------------
    def _eval_call(self, node: ast.Call, frame: _Frame) -> AbsVal:
        fname = self._func_name(node.func)
        args = [self.eval(a, frame) for a in node.args]
        kw = {k.arg: self.eval(k.value, frame) for k in node.keywords if k.arg}

        if fname == "vset":
            return self._do_vset(node, args, kw, frame)
        if fname == "seg_set":
            return self._do_seg_set(args)
        if fname == "row_set":
            return Opaque(self._args_reads(args, kw))
        if fname in ("vget", "vgather"):
            if args and isinstance(args[0], SVal):
                base, idx = args[0], args[1] if len(args) > 1 else Opaque(UNKNOWN)
                if isinstance(idx, Rng):
                    fields = frozenset(range(idx.lo, idx.hi + 1))
                    return Opaque(
                        fs_union(fs_union(base.reads, idx.reads), fields)
                    )
                return Opaque(UNKNOWN)
            return Opaque(self._args_reads(args, kw))
        if fname == "clip":
            return self._do_clip(args, kw)
        if fname in ("maximum", "minimum", "max", "min") and len(args) == 2:
            if isinstance(args[0], Rng) and isinstance(args[1], Rng):
                a, b = args[0], args[1]
                reads = fs_union(a.reads, b.reads)
                if fname in ("maximum", "max"):
                    return Rng(max(a.lo, b.lo), max(a.hi, b.hi), reads)
                return Rng(min(a.lo, b.lo), min(a.hi, b.hi), reads)
        if fname == "where" and len(args) == 3:
            if isinstance(args[1], SVal) or isinstance(args[2], SVal):
                return _merge_vals(args[1], args[2], _reads_of(args[0]))
            la = args[1].length if isinstance(args[1], Opaque) else None
            lb = args[2].length if isinstance(args[2], Opaque) else None
            return Opaque(
                self._args_reads(args, kw), length=la if la == lb else None
            )
        if fname in ("full", "zeros", "ones"):
            length = self._shape_len(node.args[0] if node.args else None, frame)
            return Opaque(self._args_reads(args, kw), length=length)
        if fname in ("zeros_like", "ones_like"):
            src = args[0] if args else Opaque()
            length = src.length if isinstance(src, Opaque) else None
            return Opaque(self._args_reads(args, kw), length=length)
        if fname in ("int32", "bool_", "asarray", "array", "astype"):
            if len(args) == 1:
                return args[0]
            return Opaque(self._args_reads(args, kw))
        if fname in _PURE_ARRAY_FNS:
            if any(isinstance(a, SVal) for a in args) or any(
                isinstance(v, SVal) for v in kw.values()
            ):
                # A state vector flowing through an un-modeled array op:
                # whatever comes out read everything we can't bound.
                return Opaque(UNKNOWN)
            return Opaque(self._args_reads(args, kw))

        # Method-style calls on abstract values (x.astype(...), .sum()).
        if isinstance(node.func, ast.Attribute):
            base = self.eval(node.func.value, frame)
            if not isinstance(base, Py):
                if isinstance(base, SVal) and node.func.attr not in (
                    "astype", "reshape", "copy",
                ):
                    return Opaque(UNKNOWN)
                reads = fs_union(_reads_of(base), self._args_reads(args, kw))
                return Opaque(reads)

        # User helper resolved to a real function object: recurse.
        target = self.eval(node.func, frame)
        if isinstance(target, Py) and inspect.isfunction(target.value):
            return self.run_fn(target.value, args, kw)

        if any(isinstance(a, SVal) for a in args) or any(
            isinstance(v, SVal) for v in kw.values()
        ):
            return Opaque(UNKNOWN)
        return Opaque(self._args_reads(args, kw))

    def _args_reads(self, args: List[AbsVal], kw: Dict[str, AbsVal]) -> FieldSet:
        reads: FieldSet = frozenset()
        for a in list(args) + list(kw.values()):
            reads = fs_union(reads, _reads_of(a))
        return reads

    def _shape_len(self, shape_ast: Optional[ast.expr], frame: _Frame
                   ) -> Optional[int]:
        if shape_ast is None:
            return None
        v = self.eval(shape_ast, frame)
        if isinstance(v, Rng):
            return v.const
        if isinstance(v, TupleVal) and len(v.items) == 1 and isinstance(
            v.items[0], Rng
        ):
            return v.items[0].const
        return None

    def _do_clip(self, args: List[AbsVal], kw: Dict[str, AbsVal]) -> AbsVal:
        vals = list(args) + [kw[k] for k in ("a_min", "a_max") if k in kw]
        if len(vals) >= 3 and isinstance(vals[1], Rng) and isinstance(
            vals[2], Rng
        ):
            lo, hi = vals[1], vals[2]
            reads = self._args_reads(args, kw)
            if isinstance(vals[0], Rng):
                return Rng(
                    max(vals[0].lo, lo.lo), min(vals[0].hi, hi.hi), reads
                )
            return Rng(lo.lo, hi.hi, reads)
        return Opaque(self._args_reads(args, kw))

    def _do_vset(self, node: ast.Call, args: List[AbsVal],
                 kw: Dict[str, AbsVal], frame: _Frame) -> AbsVal:
        if not args or not isinstance(args[0], SVal):
            return Opaque(self._args_reads(args, kw))
        base = args[0]
        idx = args[1] if len(args) > 1 else Opaque(UNKNOWN)
        val = args[2] if len(args) > 2 else Opaque(UNKNOWN)
        en = args[3] if len(args) > 3 else kw.get("enabled")
        extra = fs_union(_reads_of(val), _reads_of(en) if en else frozenset())
        extra = fs_union(extra, _reads_of(idx))
        if not isinstance(idx, Rng):
            return SVal(
                writes=UNKNOWN, or_writes=frozenset(),
                reads=fs_union(base.reads, extra), width=base.width,
            )
        fields = frozenset(range(idx.lo, idx.hi + 1))
        # Or-accumulate refinement: vset(X, C, X[C] | e1 | e2, ...) with a
        # single constant field C whose value is a BitOr chain containing
        # the self-read X[C] once, and no other read of C.
        orw = frozenset()
        if idx.const is not None and len(node.args) > 2:
            c = idx.const
            if self._is_or_accum(node.args[2], node.args[0], c, frame):
                val_reads = _reads_of(val)
                if val_reads is not UNKNOWN:
                    val_reads = val_reads - {c}
                    extra = fs_union(
                        fs_union(val_reads, _reads_of(en) if en else frozenset()),
                        idx.reads,
                    )
                    orw = frozenset({c})
                    fields = frozenset()
        plain = fs_union(base.writes, fields)
        or_all = base.or_writes | orw
        if plain is not UNKNOWN:
            or_all = or_all - plain
        else:
            or_all = frozenset()
        return SVal(
            writes=plain, or_writes=or_all,
            reads=fs_union(base.reads, extra), width=base.width,
        )

    def _is_or_accum(self, val_ast: ast.expr, base_ast: ast.expr, c: int,
                     frame: _Frame) -> bool:
        """Is ``val_ast`` a BitOr chain over exactly one self-read of
        field ``c`` of the same state expression?"""
        terms: List[ast.expr] = []

        def flatten(n: ast.expr) -> None:
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.BitOr):
                flatten(n.left)
                flatten(n.right)
            else:
                terms.append(n)

        flatten(val_ast)
        if len(terms) < 2:
            return False
        self_reads = 0
        for t in terms:
            if (
                isinstance(t, ast.Subscript)
                and ast.dump(t.value) == ast.dump(base_ast)
            ):
                idx = self.eval(t.slice, frame)
                if isinstance(idx, Rng) and idx.const == c:
                    self_reads += 1
                    continue
            v = self.eval(t, frame)
            r = _reads_of(v)
            if r is UNKNOWN or c in r:
                return False
        return self_reads == 1

    def _do_seg_set(self, args: List[AbsVal]) -> AbsVal:
        if not args or not isinstance(args[0], SVal):
            return Opaque(self._args_reads(args, {}))
        base = args[0]
        start = args[1] if len(args) > 1 else Opaque(UNKNOWN)
        seg = args[2] if len(args) > 2 else Opaque(UNKNOWN)
        extra = fs_union(_reads_of(start), _reads_of(seg))
        length = seg.length if isinstance(seg, Opaque) else None
        if isinstance(start, Rng) and start.const is not None and length:
            fields = frozenset(range(start.const, start.const + length))
        else:
            fields = UNKNOWN
        plain = fs_union(base.writes, fields)
        orw = base.or_writes - plain if plain is not UNKNOWN else frozenset()
        return SVal(
            writes=plain, or_writes=orw,
            reads=fs_union(base.reads, extra), width=base.width,
        )

    @staticmethod
    def _func_name(func: ast.expr) -> Optional[str]:
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute):
            return func.attr
        return None


# ---------------------------------------------------------------------------
# DSL app analysis
# ---------------------------------------------------------------------------

@dataclass
class AppEffects:
    """Per-message-tag effects of one DSLApp's handler.

    ``tag_code`` / ``shared_code`` are bytecode digests attributing the
    handler's code to tags: ``tag_code[t]`` digests the branch function
    tag ``t`` dispatches to (folded recursively over its closure, same
    visibility as ``persist.checkpoint.handler_fingerprint``);
    ``shared_code`` digests the dispatcher itself minus the branch
    functions. Differential exploration (``analysis/delta.py``) diffs
    these between versions to localize a change to tags; a change that
    only moves ``shared_code`` contaminates every tag. Neither field
    enters ``to_json`` — the golden effect sets stay version-stable."""

    per_tag: Dict[int, EffectSet] = field(default_factory=dict)
    default: EffectSet = field(default_factory=EffectSet.unknown)
    n_tags: int = 0
    failure: Optional[str] = None
    tag_code: Dict[int, str] = field(default_factory=dict)
    shared_code: str = ""

    @classmethod
    def unknown(cls, n_tags: int = 0, reason: str = "") -> "AppEffects":
        return cls(per_tag={}, default=EffectSet.unknown(), n_tags=n_tags,
                   failure=reason or None)

    def effect_for(self, tag: int) -> EffectSet:
        return self.per_tag.get(int(tag), self.default)

    def to_json(self) -> Dict:
        return {
            "n_tags": self.n_tags,
            "default": self.default.to_json(),
            "per_tag": {str(t): e.to_json() for t, e in sorted(self.per_tag.items())},
            "failure": self.failure,
        }


def fn_digest(fn: Optional[Callable]) -> str:
    """Bytecode digest of one function, folded recursively over its
    closure exactly like ``handler_fingerprint`` folds the whole app —
    the two see the same changes, so a delta plan never claims
    attribution the fingerprint layer cannot detect."""
    if fn is None:
        return ""
    import hashlib

    from ..persist.checkpoint import _code_digest

    h = hashlib.sha256()
    _code_digest(h, fn)
    return h.hexdigest()[:16]


def _shared_digest(handler: Callable, branch_fns: Sequence[Callable]) -> str:
    """Digest of the dispatcher minus its branch functions: the
    handler's own bytecode plus every closure cell that is not a branch
    function (or a sequence wholly of branch functions). An edit that
    moves this digest cannot be attributed to a tag, so the delta plan
    degrades to a full cone — unattributed change is never skipped."""
    import hashlib

    from ..persist.checkpoint import _code_digest

    h = hashlib.sha256()
    h.update(handler.__code__.co_code)
    bset = {id(f) for f in branch_fns}
    for cell in handler.__closure__ or ():
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if callable(v) and id(v) in bset:
            continue
        if (
            isinstance(v, (list, tuple)) and v
            and all(callable(x) and id(x) in bset for x in v)
        ):
            continue
        _code_digest(h, v)
    return h.hexdigest()[:16]


def _effect_from_result(val: AbsVal) -> EffectSet:
    """EffectSet of a handler's returned (state', outbox) pair."""
    if isinstance(val, TupleVal) and val.items:
        sv = val.items[0]
        out_reads: FieldSet = frozenset()
        for other in val.items[1:]:
            out_reads = fs_union(out_reads, _reads_of(other))
    else:
        sv, out_reads = val, frozenset()
    if isinstance(sv, SVal):
        return EffectSet(
            reads=fs_union(sv.reads, out_reads), writes=sv.writes,
            or_writes=sv.or_writes,
        )
    return EffectSet(reads=UNKNOWN, writes=UNKNOWN)


def _find_switch(node: ast.AST) -> Optional[ast.Call]:
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "switch"
        ):
            return sub
    return None


def _tag_index_fn(tag_ast: ast.expr, frame: _Frame, interp: _Interp,
                  msg_name: str) -> Optional[Callable[[int], Optional[int]]]:
    """Compile the switch selector expression into tag -> branch index,
    understanding ``msg[0]``, +/- constants, and jnp.clip. Returns None
    when the selector is not recognized (conservative: all branches)."""

    def build(n: ast.expr) -> Optional[Callable[[int], Optional[int]]]:
        if isinstance(n, ast.Name):
            src = frame.ast_defs.get(n.id)
            return build(src) if src is not None else None
        if (
            isinstance(n, ast.Subscript)
            and isinstance(n.value, ast.Name)
            and n.value.id == msg_name
        ):
            idx = interp.eval(n.slice, frame)
            if isinstance(idx, Rng) and idx.const == 0:
                return lambda t: t
            return None
        if isinstance(n, ast.BinOp) and isinstance(n.op, (ast.Add, ast.Sub)):
            inner = build(n.left)
            off = interp.eval(n.right, frame)
            if inner is None or not (
                isinstance(off, Rng) and off.const is not None
            ):
                return None
            k = off.const if isinstance(n.op, ast.Add) else -off.const

            def shifted(t, inner=inner, k=k):
                v = inner(t)
                return None if v is None else v + k

            return shifted
        if isinstance(n, ast.Call):
            fname = _Interp._func_name(n.func)
            if fname == "clip" and len(n.args) == 3:
                inner = build(n.args[0])
                lo = interp.eval(n.args[1], frame)
                hi = interp.eval(n.args[2], frame)
                if inner is None or not (
                    isinstance(lo, Rng) and lo.const is not None
                    and isinstance(hi, Rng) and hi.const is not None
                ):
                    return None

                def clipped(t, inner=inner, lo=lo.const, hi=hi.const):
                    v = inner(t)
                    return None if v is None else max(lo, min(hi, v))

                return clipped
            if fname in ("int32", "asarray", "astype") and n.args:
                return build(n.args[0])
        return None

    return build(tag_ast)


def analyze_dsl_app(app) -> AppEffects:
    """Per-tag effect extraction for a DSLApp (see module docstring).
    Never raises: any failure returns ``AppEffects.unknown``."""
    n_tags = max(
        len(app.tag_names) - 1 if app.tag_names else 0,
        max(app.timer_tags) if app.timer_tags else 0,
    )
    try:
        return _analyze_dsl_handler(app.handler, n_tags)
    except (_Bail, OSError, TypeError, SyntaxError, ValueError,
            RecursionError) as exc:
        return AppEffects.unknown(n_tags, f"{type(exc).__name__}: {exc}")


def _analyze_dsl_handler(handler: Callable, n_tags: int) -> AppEffects:
    node = _fn_ast(handler)
    interp = _Interp()
    frame = _Frame(_fn_env(handler), 0)
    params = [a.arg for a in node.args.posonlyargs + node.args.args]
    if len(params) < 4:
        raise _Bail("handler does not take (actor_id, state, snd, msg)")
    actor_p, state_p, snd_p, msg_p = params[:4]
    frame.locals[actor_p] = Opaque()
    frame.locals[state_p] = SVal()
    frame.locals[snd_p] = Opaque()
    frame.locals[msg_p] = Opaque()

    switch = _find_switch(node)
    if switch is None:
        interp._stack.append(handler)
        try:
            interp.exec_block(node.body, frame)
        finally:
            interp._stack.pop()
        if not frame.returns:
            raise _Bail("handler has no return")
        merged = frame.returns[0]
        for r in frame.returns[1:]:
            merged = _merge_vals(merged, r, frozenset())
        eff = _effect_from_result(merged)
        # No dispatch to attribute code to: the whole handler is shared,
        # so any edit contaminates every tag (sound, not localized).
        return AppEffects(
            per_tag={t: eff for t in range(0, n_tags + 1)},
            default=eff, n_tags=n_tags,
            shared_code=fn_digest(handler),
        )

    # Execute the preamble: every statement up to (excluding) the one
    # containing the switch call. The switch is conventionally in the
    # final return / assignment.
    interp._stack.append(handler)
    try:
        for st in node.body:
            if any(sub is switch for sub in ast.walk(st)):
                break
            interp.exec_stmt(st, frame)
    finally:
        interp._stack.pop()

    pre_state = frame.locals.get(state_p)
    if not isinstance(pre_state, SVal):
        raise _Bail("preamble lost track of the state value")
    pre_eff = EffectSet(
        reads=pre_state.reads, writes=pre_state.writes,
        or_writes=pre_state.or_writes,
    )
    if pre_eff.is_unknown():
        raise _Bail("preamble effects unknown")

    if len(switch.args) < 2:
        raise _Bail("switch without branches")
    branches_val = interp.eval(switch.args[1], frame)
    branch_fns: List[Callable] = []
    if isinstance(branches_val, TupleVal):
        for item in branches_val.items:
            if isinstance(item, Py) and inspect.isfunction(item.value):
                branch_fns.append(item.value)
            else:
                raise _Bail("switch branch is not a resolvable function")
    elif isinstance(branches_val, Py) and isinstance(
        branches_val.value, (list, tuple)
    ):
        for f in branches_val.value:
            if not inspect.isfunction(f):
                raise _Bail("switch branch is not a function")
            branch_fns.append(f)
    else:
        raise _Bail("switch branches not statically resolvable")

    # Operands passed to each branch (positionally after the branch list).
    operand_vals = [interp.eval(a, frame) for a in switch.args[2:]]

    branch_effects: List[EffectSet] = []
    for fn in branch_fns:
        result = interp.run_fn(fn, list(operand_vals))
        branch_effects.append(_effect_from_result(result))

    union_all = branch_effects[0]
    for be in branch_effects[1:]:
        union_all = union_all.union(be)

    branch_digests = [fn_digest(fn) for fn in branch_fns]
    import hashlib as _hl

    union_digest = _hl.sha256(
        ("|".join(branch_digests)).encode()
    ).hexdigest()[:16]

    tag_to_idx = _tag_index_fn(switch.args[0], frame, interp, msg_p)
    per_tag: Dict[int, EffectSet] = {}
    tag_code: Dict[int, str] = {}
    for t in range(0, n_tags + 1):
        if tag_to_idx is None:
            per_tag[t] = union_all
            tag_code[t] = union_digest
            continue
        idx = tag_to_idx(t)
        if idx is None or not (0 <= idx < len(branch_effects)):
            per_tag[t] = union_all
            tag_code[t] = union_digest
        else:
            per_tag[t] = branch_effects[idx]
            tag_code[t] = branch_digests[idx]
    return AppEffects(
        per_tag=per_tag, default=union_all, n_tags=n_tags,
        tag_code=tag_code, shared_code=_shared_digest(handler, branch_fns),
    )


# ---------------------------------------------------------------------------
# Host Actor-class analysis (attribute-level)
# ---------------------------------------------------------------------------

@dataclass
class ActorEffects:
    """Per-message-type attribute effects of a host Actor class.
    ``per_type`` keys are the dispatch constants (``msg[0] == <const>``
    values or isinstance class names); ``default`` covers everything
    else."""

    per_type: Dict[Any, EffectSet] = field(default_factory=dict)
    default: EffectSet = field(default_factory=EffectSet.unknown)
    failure: Optional[str] = None

    @classmethod
    def unknown(cls, reason: str = "") -> "ActorEffects":
        return cls(failure=reason or None)

    def effect_for(self, type_key: Any) -> EffectSet:
        return self.per_type.get(type_key, self.default)


class _AttrScan(ast.NodeVisitor):
    """reads/writes over ``self.<attr>`` in one statement block;
    anything dynamic — setattr/vars, self-method calls, or a
    ``self.<attr>`` value ESCAPING into an alias or a call argument
    (through which a container could be mutated without an attribute
    store appearing here) — degrades the whole block to unknown."""

    _PURE_RECEIVERS = {
        "get", "keys", "values", "items", "count", "index", "copy",
        "startswith", "endswith", "split", "join", "format",
    }

    def __init__(self):
        self.reads: set = set()
        self.writes: set = set()
        self.unknown = False

    def scan(self, stmts: List[ast.stmt]) -> EffectSet:
        for st in stmts:
            self.visit(st)
        if self.unknown:
            return EffectSet.unknown()
        return EffectSet(
            reads=frozenset(self.reads), writes=frozenset(self.writes)
        )

    def _self_attr(self, node: ast.expr) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._self_attr(node)
        if attr is not None:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self.writes.add(attr)
            else:
                self.reads.add(attr)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        attr = self._self_attr(node.value)
        if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            self.writes.add(attr)
            self.reads.add(attr)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            attr = self._self_attr(node.func.value)
            if attr is not None:
                if node.func.attr in (
                    "append", "extend", "insert", "pop", "remove", "clear",
                    "update", "setdefault", "add", "discard", "sort",
                    "reverse",
                ):
                    self.writes.add(attr)
                    self.reads.add(attr)
                elif node.func.attr not in self._PURE_RECEIVERS:
                    # Unrecognized method on a self-attr container: it
                    # may mutate in place.
                    self.unknown = True
            if (
                isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr not in ("checkpoint_state",)
            ):
                # A self-method call may touch anything.
                self.unknown = True
        if isinstance(node.func, ast.Name) and node.func.id in (
            "setattr", "getattr", "delattr", "vars",
        ):
            self.unknown = True
        # A self-attr value escaping as a call ARGUMENT may be mutated
        # or retained by the callee (no attribute store appears in this
        # block) — unless the callee is a known-pure builtin.
        callee = node.func.id if isinstance(node.func, ast.Name) else None
        if callee not in self._PURE_BUILTINS:
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for sub in ast.walk(arg):
                    if self._self_attr(sub) is not None:
                        self.unknown = True
        self.generic_visit(node)

    _PURE_BUILTINS = {
        "len", "list", "tuple", "set", "frozenset", "dict", "sorted",
        "sum", "min", "max", "any", "all", "str", "repr", "int", "float",
        "bool", "enumerate", "zip", "reversed", "abs", "isinstance",
        "hash", "range",
    }

    def _escaping(self, node: ast.expr) -> bool:
        """Could this assigned value alias a self-attr container (so a
        later mutation through the alias bypasses this scan)? Direct
        attrs, attribute/subscript chains off them, and containers
        holding them escape; arithmetic/comparison results are consumed
        by value."""
        if self._self_attr(node) is not None:
            return True
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._escaping(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(
                self._escaping(e) for e in list(node.keys) + list(node.values)
                if e is not None
            )
        if isinstance(node, ast.Starred):
            return self._escaping(node.value)
        if isinstance(node, (ast.Subscript, ast.Attribute)):
            return self._escaping(node.value)
        if isinstance(node, ast.IfExp):
            return self._escaping(node.body) or self._escaping(node.orelse)
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        # Aliasing: `q = self.queue` / `st = self.state[actor]` lets
        # later statements mutate the container through the alias —
        # degrade rather than track aliases. Consumed-by-value uses
        # (`n = self.count + 1`) stay precise.
        if self._escaping(node.value):
            self.unknown = True
        self.generic_visit(node)


def _dispatch_key(test: ast.expr, msg_name: str) -> Optional[Any]:
    """The dispatch constant of ``msg[0] == <const>`` or
    ``isinstance(msg, T)`` tests."""
    if (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.Eq)
    ):
        for a, b in ((test.left, test.comparators[0]),
                     (test.comparators[0], test.left)):
            if (
                isinstance(a, ast.Subscript)
                and isinstance(a.value, ast.Name)
                and a.value.id == msg_name
                and isinstance(a.slice, ast.Constant)
                and a.slice.value == 0
                and isinstance(b, ast.Constant)
            ):
                return b.value
    if (
        isinstance(test, ast.Call)
        and isinstance(test.func, ast.Name)
        and test.func.id == "isinstance"
        and len(test.args) == 2
        and isinstance(test.args[0], ast.Name)
        and test.args[0].id == msg_name
        and isinstance(test.args[1], ast.Name)
    ):
        return test.args[1].id
    return None


def analyze_actor_class(cls) -> ActorEffects:
    """Attribute-level per-message-type effects of an Actor class's
    ``receive``. Never raises."""
    try:
        receive = cls.__dict__.get("receive") or getattr(cls, "receive")
        node = _fn_ast(receive)
    except (OSError, TypeError, AttributeError, SyntaxError, _Bail) as exc:
        return ActorEffects.unknown(f"{type(exc).__name__}: {exc}")
    params = [a.arg for a in node.args.posonlyargs + node.args.args]
    if len(params) < 4:
        return ActorEffects.unknown("receive signature not (self, ctx, snd, msg)")
    msg_name = params[3]

    # Top-level dispatch chain: if <key-test>: ... elif ...: ... else ...
    per_type: Dict[Any, EffectSet] = {}
    residue: List[ast.stmt] = []
    only_dispatch = True
    for st in node.body:
        if isinstance(st, ast.If):
            chain_ok = True
            cur: Optional[ast.stmt] = st
            branches: List[Tuple[Any, List[ast.stmt]]] = []
            while isinstance(cur, ast.If):
                key = _dispatch_key(cur.test, msg_name)
                if key is None:
                    chain_ok = False
                    break
                branches.append((key, cur.body))
                if len(cur.orelse) == 1 and isinstance(cur.orelse[0], ast.If):
                    cur = cur.orelse[0]
                else:
                    residue.extend(cur.orelse)
                    cur = None
            if chain_ok:
                for key, body in branches:
                    eff = _AttrScan().scan(body)
                    per_type[key] = (
                        per_type[key].union(eff) if key in per_type else eff
                    )
                continue
        only_dispatch = False
        residue.append(st)

    if not per_type:
        return ActorEffects(per_type={}, default=_AttrScan().scan(node.body))
    # Residue statements (shared pre/post code) apply to every type; an
    # unrecognized message type gets the whole method's effects.
    residue_eff = _AttrScan().scan(residue) if residue else EffectSet()
    per_type = {k: e.union(residue_eff) for k, e in per_type.items()}
    return ActorEffects(
        per_type=per_type, default=_AttrScan().scan(node.body)
    )
