"""Runtime replay sanitizer (``DEMI_SANITIZE=1`` / ``--sanitize``).

The determinism lint (analysis/lint.py) is static and can only *suspect*
some replay-breakers; this wraps the host tier's handler dispatch to
catch them as they happen:

  - **in-place message mutation** — every pending message is digested at
    capture time and re-digested at delivery; the delivered message is
    digested before and after the handler runs. A mismatch means some
    handler mutated an object the trace recorder / peek rollback shares
    (``analysis.sanitizer_mutations{where=pending|receive}``).
  - **wall-clock / process-global randomness traps** — ``time.time``-
    family and module-level ``random``/``uuid4``/``os.urandom`` calls
    made *while a handler is executing* are counted
    (``analysis.sanitizer_time_reads`` / ``analysis.sanitizer_random_draws``)
    and, in strict mode, rejected.

Modes: ``observe`` (count + one warning per site; the ``DEMI_SANITIZE=1``
default) and ``strict`` (``DEMI_SANITIZE=strict`` or ``--sanitize`` on
``demi_tpu replay``): a trap or mutation raises ``SanitizerError`` —
a HarnessError subclass, so ``deliver()`` re-raises it instead of
converting the nondeterminism into actor-crash semantics. Strict is the
right mode for strict replay, where a nondeterministic handler silently
invalidates the bit-exactness the whole pipeline rests on.

The traps only patch while a handler is on the stack (the event loop is
sequential by construction), so framework timing code — obs spans,
host-share ledgers, kernel-compile internals — is never intercepted.
"""

from __future__ import annotations

import hashlib
import logging
import os
import random as _random_mod
import struct
import time as _time_mod
import uuid as _uuid_mod
from typing import Any, Callable, Dict, Optional

from ..runtime.system import HarnessError

_log = logging.getLogger("demi_tpu.sanitize")


class SanitizerError(HarnessError):
    """Nondeterminism detected under strict sanitization. A HarnessError:
    the run's results can no longer be trusted as deterministic, which is
    infrastructure-level, not an application crash."""


_mode: Optional[str] = None
_mode_resolved = False


def _env_mode() -> Optional[str]:
    raw = os.environ.get("DEMI_SANITIZE", "").strip().lower()
    if raw in ("strict", "2"):
        return "strict"
    if raw in ("1", "true", "yes", "on", "observe"):
        return "observe"
    return None


def enable(strict: bool = False) -> None:
    global _mode, _mode_resolved
    _mode = "strict" if strict else "observe"
    _mode_resolved = True


def disable() -> None:
    global _mode, _mode_resolved
    _mode = None
    _mode_resolved = True


def reset() -> None:
    """Forget any explicit enable()/disable(): resolution returns to the
    DEMI_SANITIZE env var (test / CLI hygiene)."""
    global _mode, _mode_resolved
    _mode = None
    _mode_resolved = False


def mode() -> Optional[str]:
    """'observe' / 'strict' / None. Explicit enable()/disable() wins;
    otherwise the DEMI_SANITIZE env var is re-read (the CLI sets it)."""
    if _mode_resolved:
        return _mode
    return _env_mode()


def enabled() -> bool:
    return mode() is not None


# -- structural digests ------------------------------------------------------

def digest(obj: Any) -> bytes:
    """Stable structural digest of a message object: containers recurse,
    numpy arrays hash their bytes, everything else falls back to a
    scrubbed repr. Equal digests <=> structurally equal content (up to
    blake2b-16 collisions), and crucially: MUTATION changes the digest
    while object identity does not."""
    h = hashlib.blake2b(digest_size=16)
    _feed(h, obj, 0)
    return h.digest()


def _feed(h, obj: Any, depth: int) -> None:
    if depth > 16:
        h.update(b"<deep>")
        return
    if obj is None or isinstance(obj, (bool, int, str, bytes)):
        h.update(f"{type(obj).__name__}:{obj!r};".encode())
        return
    if isinstance(obj, float):
        h.update(b"f" + struct.pack("<d", obj))
        return
    if isinstance(obj, (tuple, list)):
        h.update(f"{type(obj).__name__}[{len(obj)}](".encode())
        for item in obj:
            _feed(h, item, depth + 1)
        h.update(b")")
        return
    if isinstance(obj, dict):
        h.update(f"dict[{len(obj)}](".encode())
        try:
            items = sorted(obj.items(), key=lambda kv: repr(kv[0]))
        except Exception:
            items = list(obj.items())
        for k, v in items:
            _feed(h, k, depth + 1)
            _feed(h, v, depth + 1)
        h.update(b")")
        return
    if isinstance(obj, (set, frozenset)):
        h.update(f"set[{len(obj)}](".encode())
        for r in sorted(repr(x) for x in obj):
            h.update(r.encode())
        h.update(b")")
        return
    if hasattr(obj, "__dataclass_fields__"):
        h.update(f"dc:{type(obj).__name__}(".encode())
        for f in obj.__dataclass_fields__:
            _feed(h, getattr(obj, f), depth + 1)
        h.update(b")")
        return
    tobytes = getattr(obj, "tobytes", None)
    if callable(tobytes):
        try:
            h.update(b"arr:" + tobytes())
            return
        except Exception:
            pass
    import re

    h.update(re.sub(r"0x[0-9a-fA-F]+", "<addr>", repr(obj)).encode())


# -- stats -------------------------------------------------------------------

_stats: Dict[str, int] = {
    "mutations_receive": 0,
    "mutations_pending": 0,
    "time_reads": 0,
    "random_draws": 0,
}
_warned_sites: set = set()


def stats() -> Dict[str, int]:
    return dict(_stats)


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0
    _warned_sites.clear()


_in_note = False


def _note(kind: str, site: str, detail: str, strict: bool) -> None:
    global _in_note
    from .. import obs

    if kind.startswith("mutations"):
        _stats[kind] += 1
        where = kind.split("_", 1)[1]
        obs.counter("analysis.sanitizer_mutations").inc(where=where)
    elif kind == "time_reads":
        _stats[kind] += 1
        obs.counter("analysis.sanitizer_time_reads").inc(fn=site)
    else:
        _stats[kind] += 1
        obs.counter("analysis.sanitizer_random_draws").inc(fn=site)
    if strict:
        raise SanitizerError(f"sanitizer ({kind}): {detail}")
    if site not in _warned_sites:
        _warned_sites.add(site)
        # The logging machinery itself timestamps records with
        # time.time(); _in_note keeps that internal read from counting
        # as handler nondeterminism while the traps are armed.
        _in_note = True
        try:
            _log.warning("demi_tpu sanitizer: %s (%s)", detail, site)
        finally:
            _in_note = False


# -- handler-scope traps -----------------------------------------------------

# Library internals whose clock/random reads are NOT app nondeterminism:
# jax's dispatch/compile machinery timestamps every first-call compile
# (20+ time.time() reads per jit), and logging stamps records. Trapped
# calls whose immediate caller lives in these packages pass through
# uncounted — otherwise strict replay of any DSL app would abort on its
# first (compiling) delivery.
_EXEMPT_CALLER_PKGS = {
    "jax", "jaxlib", "logging", "importlib", "absl", "etils", "threading",
}

_TIME_FNS = ("time", "time_ns")
_RANDOM_FNS = (
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "getrandbits",
)


class _Traps:
    """Patch wall-clock / global-random entry points for the duration of
    one handler call; restore unconditionally."""

    def __init__(self, rcv: str, strict: bool):
        self.rcv = rcv
        self.strict = strict
        self._saved = []

    def _wrap(self, module, name: str, kind: str):
        original = getattr(module, name)
        site = f"{module.__name__}.{name}"
        rcv, strict = self.rcv, self.strict

        def trapped(*args, **kwargs):
            if _in_note:  # sanitizer-internal (logging timestamp) call
                return original(*args, **kwargs)
            import sys

            caller = sys._getframe(1).f_globals.get("__name__", "")
            if caller.partition(".")[0] in _EXEMPT_CALLER_PKGS:
                return original(*args, **kwargs)
            _note(
                kind, site,
                f"handler of {rcv!r} called {site}() — replay-breaking "
                "nondeterminism (see `demi_tpu lint`)",
                strict,
            )
            return original(*args, **kwargs)

        self._saved.append((module, name, original))
        setattr(module, name, trapped)

    def __enter__(self):
        for name in _TIME_FNS:
            self._wrap(_time_mod, name, "time_reads")
        for name in _RANDOM_FNS:
            self._wrap(_random_mod, name, "random_draws")
        self._wrap(_uuid_mod, "uuid4", "random_draws")
        self._wrap(os, "urandom", "random_draws")
        return self

    def __exit__(self, *exc):
        for module, name, original in reversed(self._saved):
            setattr(module, name, original)
        self._saved.clear()
        return False


# -- the dispatch wrapper (what runtime/system.py calls) --------------------

class Sanitizer:
    def __init__(self, strict: bool):
        self.strict = strict

    def seal(self, msg: Any) -> bytes:
        return digest(msg)

    def check_pending(self, entry) -> None:
        """Capture-time vs delivery-time digest: catches a sender (or
        anyone holding the reference) mutating a message while it sat in
        the pending set."""
        sealed = getattr(entry, "sent_digest", None)
        if sealed is None:
            return
        if digest(entry.msg) != sealed:
            _note(
                "mutations_pending", f"pending:{entry.rcv}",
                f"message {entry.snd!r}->{entry.rcv!r} changed while "
                "pending (mutated after send)",
                self.strict,
            )

    def run(self, handler: Callable, ctx, entry) -> Any:
        """Execute one delivery's handler under the traps, then verify
        the received message was not mutated in place."""
        pre = digest(entry.msg)
        try:
            with _Traps(entry.rcv, self.strict):
                return handler(ctx)
        finally:
            if digest(entry.msg) != pre:
                _note(
                    "mutations_receive", f"receive:{entry.rcv}",
                    f"handler of {entry.rcv!r} mutated the received "
                    "message in place",
                    self.strict,
                )


_OBSERVE = Sanitizer(strict=False)
_STRICT = Sanitizer(strict=True)


def active() -> Optional[Sanitizer]:
    """The process Sanitizer when enabled, else None. Singletons — the
    runtime resolves this once per delivery / capture window, so the
    disabled path costs one env read and no allocation."""
    m = mode()
    if m is None:
        return None
    return _STRICT if m == "strict" else _OBSERVE
