"""Differential exploration: effect-diff class transfer (ROADMAP item 2).

The CI-scale product shape: when a tenant resubmits a *changed* system,
don't re-explore from scratch. A published class-store segment carries
an **effect-signature manifest** — per delivery tag, the digest of the
handler branch that tag dispatches to plus its read/write field sets
(``analysis/effects.py``), alongside digests of the dispatcher's shared
code, the invariant, and the init state. On warm start against a
changed app, ``compute_delta`` diffs stored vs current signatures into
a ``DeltaPlan``:

- **changed tags**: tags whose branch digest or effect sets moved;
- **contaminated cone**: the changed tags, closed transitively over
  field flow — when a change MOVES a field set, any tag reading one of
  the moved fields joins the cone and contributes its own writes, to a
  fixpoint. A pure code change with identical field sets keeps the
  cone at exactly the changed tags: the class-key delivery footprint is
  then precisely the invalidation criterion.
- **degradations** (sound by construction): ``unknown`` effects on
  either side, a moved shared/invariant/init digest, a tag-shape
  mismatch, or a changed tag with unknown field sets all contaminate
  everything — the plan goes ``full`` and the run is a scratch run.

``delta_warm_start`` then splits the stored classes against the cone at
**reversal-chain granularity**. Every class of a seeded exploration is
the seed prescription (the trunk) plus a chain of race reversals — one
per ancestry generation, each reordering exactly one dependent pair of
deliveries. The sleep set records that chain's tag footprint AT
ADMISSION, when the pair is exact knowledge, as ``dmask`` in the class
meta: the OR of ``tag_bit`` over BOTH rows of every reversed pair along
the class's derivation (see ``SleepSets.class_meta``). The transfer
test is ``dmask & cone_mask``: a class none of whose reversals involve
a cone tag TRANSFERS (``SleepSets.seed_covered`` — never re-executed);
a class whose chain touches the cone is RE-SEEDED onto the frontier via
its stored guide and re-explored for real. The trunk itself
(``TRUNK_BIT`` set, zero reversals) is ALWAYS re-seeded — its
re-execution under the edited app is the one run that revalidates the
shared schedule content every transferred class leans on. Classes with
no retained guide or no recorded chain (``dmask == -1``) fall back to
the full-key mask, which is strictly more conservative. Content lane
keys (``key_mode='content'``) make each re-execution bit-identical to
the scratch run's execution of the same prescription regardless of
round position, which is what lets ``--diff-audit`` demand equality,
not similarity: a full scratch exploration of the changed app must
yield the same class set, violation codes, and per-code canonical
witness digests as the differential run (``bench.py --config 17``).

Soundness caveat, stated where it matters: the chain mask covers the
REORDERINGS that distinguish a class from the trunk — the trunk content
every class replays (divergence-tolerant steering re-delivers the
source lane's remaining rows in order), including any cone-tag
deliveries in it, ran under the old binary and is vouched for by the
trunk revalidation plus the audit mode, not by the mask alone.
``unknown`` anywhere degrades to full scratch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .effects import analyze_dsl_app, fn_digest
from .sleep import TRUNK_BIT, class_tag_mask, tag_bit

MANIFEST_VERSION = 1


def effect_manifest(app) -> Dict[str, Any]:
    """Per-tag effect-signature manifest of one DSLApp — the record a
    class-store segment carries so a LATER version can compute what its
    change contaminated. JSON-able and deterministic for a given app
    version."""
    from ..persist.checkpoint import handler_fingerprint

    eff = analyze_dsl_app(app)
    unknown = eff.failure is not None or not eff.per_tag
    tags: Dict[str, Any] = {}
    if not unknown:
        for t in sorted(eff.per_tag):
            e = eff.per_tag[t]
            tags[str(t)] = {
                "code": eff.tag_code.get(t, ""),
                "effects": e.to_json(),
            }
    return {
        "version": MANIFEST_VERSION,
        "fp": handler_fingerprint(app),
        "app": str(getattr(app, "name", "")),
        "actors": int(getattr(app, "num_actors", 0)),
        "n_tags": int(eff.n_tags),
        "unknown": bool(unknown),
        "failure": eff.failure,
        "shared": eff.shared_code,
        "invariant": fn_digest(getattr(app, "invariant", None)),
        "init": fn_digest(getattr(app, "init_state", None)),
        "tags": tags,
    }


@dataclass
class DeltaPlan:
    """What a code change contaminated, per ``compute_delta``."""

    full: bool
    reason: str = ""
    changed_tags: List[int] = field(default_factory=list)
    cone_tags: List[int] = field(default_factory=list)
    cone_mask: int = 0
    diff_fields: List[int] = field(default_factory=list)
    stored_fp: str = ""
    current_fp: str = ""

    def to_json(self) -> Dict[str, Any]:
        return {
            "full": self.full,
            "reason": self.reason,
            "changed_tags": list(self.changed_tags),
            "cone_tags": list(self.cone_tags),
            "cone_mask": int(self.cone_mask),
            "diff_fields": list(self.diff_fields),
            "stored_fp": self.stored_fp,
            "current_fp": self.current_fp,
        }


def _fields(sets: Dict[str, Any], kind: str) -> Optional[Set[int]]:
    v = sets.get(kind, "unknown")
    if v == "unknown":
        return None
    return {int(x) for x in v}


def compute_delta(
    stored: Optional[Dict[str, Any]], current: Optional[Dict[str, Any]]
) -> DeltaPlan:
    """Diff two effect-signature manifests into a ``DeltaPlan``. Every
    unanalyzable situation returns ``full=True`` — the differential
    path only ever SHRINKS work when it can prove the shrink."""

    def full(reason: str) -> DeltaPlan:
        return DeltaPlan(
            full=True, reason=reason,
            stored_fp=(stored or {}).get("fp", ""),
            current_fp=(current or {}).get("fp", ""),
        )

    if not stored or not current:
        return full("missing manifest")
    if stored.get("version") != current.get("version"):
        return full("manifest version mismatch")
    if stored.get("unknown") or current.get("unknown"):
        return full(
            "unknown effects: "
            + str(stored.get("failure") or current.get("failure") or "")
        )
    for k in ("app", "actors", "n_tags"):
        if stored.get(k) != current.get(k):
            return full(f"shape mismatch: {k}")
    for k in ("shared", "invariant", "init"):
        if stored.get(k) != current.get(k):
            return full(f"unattributable change: {k} digest moved")
    st, ct = stored.get("tags", {}), current.get("tags", {})
    if set(st) != set(ct):
        return full("tag set mismatch")

    changed: List[int] = []
    diff_fields: Set[int] = set()
    for key in sorted(st, key=int):
        a, b = st[key], ct[key]
        if a == b:
            continue
        t = int(key)
        changed.append(t)
        ea, eb = a.get("effects", {}), b.get("effects", {})
        for kind in ("reads", "writes", "or_writes"):
            fa, fb = _fields(ea, kind), _fields(eb, kind)
            if fa is None or fb is None:
                return full(f"changed tag {t} has unknown {kind}")
            diff_fields |= fa ^ fb
    if not changed:
        if stored.get("fp") == current.get("fp"):
            # Bit-identical code: empty cone, everything transfers.
            return DeltaPlan(
                full=False,
                stored_fp=stored.get("fp", ""),
                current_fp=current.get("fp", ""),
            )
        # Same signatures under a different fingerprint (e.g. the
        # change was outside the handler's visible surface): nothing
        # provably moved tag-locally, but the fingerprint layer saw
        # SOMETHING move that effects could not attribute.
        return full("fingerprint moved without attributable tag change")

    # Transitive field-flow closure (only field-set DIFFS propagate —
    # see module doc): a tag reading a contaminated field joins the
    # cone and contributes its writes.
    cone: Set[int] = set(changed)
    frontier = set(diff_fields)
    while True:
        grew = False
        for key in sorted(ct, key=int):
            t = int(key)
            if t in cone:
                continue
            e = ct[key].get("effects", {})
            reads = _fields(e, "reads")
            writes = _fields(e, "writes")
            orw = _fields(e, "or_writes") or set()
            if reads is None or writes is None:
                if frontier:
                    cone.add(t)
                    grew = True
                continue
            if reads & frontier or writes & frontier or orw & frontier:
                cone.add(t)
                new_fields = (writes | orw) - frontier
                if new_fields:
                    frontier |= new_fields
                grew = True
        if not grew:
            break

    cone_tags = sorted(cone)
    mask = 0
    for t in cone_tags:
        mask |= tag_bit(t)
    return DeltaPlan(
        full=False,
        changed_tags=sorted(changed),
        cone_tags=cone_tags,
        cone_mask=mask,
        diff_fields=sorted(diff_fields),
        stored_fp=stored.get("fp", ""),
        current_fp=current.get("fp", ""),
    )


def _ledger_mask(led, key: tuple) -> int:
    meta = led.meta.get(key)
    return int(meta[0]) if meta is not None else class_tag_mask(key)


def split_transfer(led, plan: DeltaPlan) -> Tuple[List[tuple], List[tuple]]:
    """Partition a stored ledger's classes against the plan's cone:
    (transferable, cone). Full plans transfer nothing. With a retained
    guide and a recorded reversal-chain mask the test is
    ``dmask & cone_mask`` (``TRUNK_BIT`` always cones — trunk
    revalidation); otherwise the full-key mask — a superset of any
    chain's footprint, so the fallback only ever moves classes INTO the
    cone."""
    if plan.full:
        return [], sorted(led.classes)
    transfer, cone = [], []
    for k in sorted(led.classes):
        meta = led.meta.get(k)
        guide = meta[2] if meta is not None else None
        dmask = int(meta[3]) if meta is not None and len(meta) > 3 else -1
        if guide is not None and dmask >= 0:
            contaminated = bool(dmask & (plan.cone_mask | TRUNK_BIT))
        else:
            contaminated = bool(_ledger_mask(led, k) & plan.cone_mask)
        (cone if contaminated else transfer).append(k)
    return transfer, cone


def delta_warm_start(dpor, store, app) -> Optional[Dict[str, Any]]:
    """Version-aware warm start for one DeviceDPOR against a
    ``ClassStore``. Returns a stats dict (also emitted as a
    ``dpor.delta`` journal record), or None when there is nothing to
    start from (no own-fp segments AND no sibling version) — the caller
    then runs scratch.

    - Own-fingerprint segments exist → **exact** mode: plain covered
      warm start (the PR 13 path) + full violation inheritance.
    - Else the best sibling version (most transferable classes) is
      diffed: transferable classes are seeded covered; cone classes
      that EXECUTED in the stored run are re-seeded onto the frontier
      with their stored guides (bit-identical re-execution under
      content lane keys); cone classes the stored run only admitted
      but never executed are noted un-executed, exactly matching what
      a scratch run would observe of them. Violation codes whose
      canonical witness class avoids the cone are inherited with their
      witness; cone-witnessed codes must be re-found live."""
    from .. import obs

    sleep = getattr(dpor, "sleep", None)
    if sleep is None:
        return None
    current = effect_manifest(app)
    own = store.load()
    stats: Dict[str, Any]
    if own.classes:
        sleep.seed_covered(own.classes, meta=own.meta)
        inherited_w = dict(own.witnesses)
        stats = {
            "mode": "exact",
            "full": False,
            "from_fp": store.workload_fp,
            "to_fp": current.get("fp", ""),
            "changed_tags": [],
            "cone_tags": [],
            "stored_classes": len(own.classes),
            "transferred": len(own.classes),
            "reseeded": 0,
            "pending": len(own.pending),
            "unseedable": 0,
            "inherited_codes": sorted(int(c) for c in own.violation_codes),
            "inherited_witnesses": inherited_w,
        }
    else:
        best = None
        for fp in store.sibling_fps():
            led = store.load_fp(fp)
            if not led.classes:
                continue
            plan = compute_delta(led.manifest, current)
            transfer, cone = split_transfer(led, plan)
            cand = (len(transfer), fp, led, plan, transfer, cone)
            if best is None or cand[0] > best[0] or (
                cand[0] == best[0] and fp < best[1]
            ):
                best = cand
        if best is None:
            return None
        _, from_fp, led, plan, transfer, cone = best
        stats = {
            "mode": "delta",
            "full": plan.full,
            "reason": plan.reason,
            "from_fp": from_fp,
            "to_fp": current.get("fp", ""),
            "changed_tags": plan.changed_tags,
            "cone_tags": plan.cone_tags,
            "diff_fields": plan.diff_fields,
            "stored_classes": len(led.classes),
            "transferred": 0,
            "reseeded": 0,
            "pending": 0,
            "unseedable": 0,
            "inherited_codes": [],
            "inherited_witnesses": {},
        }
        if not plan.full:
            cone_set = set(cone)
            sleep.seed_covered(transfer, meta=led.meta)
            stats["transferred"] = len(transfer)
            reseeded = unseedable = pending_noted = 0
            from ..native import prescription_digest

            for k in cone:
                if k in sleep.classes:
                    continue
                meta = led.meta.get(k)
                if k in led.pending:
                    # Admitted but never executed in the stored run: a
                    # scratch run of the old version would not have
                    # executed it either — note it, don't run it.
                    sleep.note_class(k)
                    if meta is not None:
                        sleep.adopt_meta({k: meta})
                    pending_noted += 1
                    continue
                if meta is None or meta[2] is None:
                    unseedable += 1
                    continue
                plen, guide = meta[1], meta[2]
                dm = int(meta[3]) if len(meta) > 3 else -1
                rep = tuple(tuple(int(x) for x in r) for r in guide[:plen])
                sleep.note_class(k, guide=guide, plen=plen, dmask=dm)
                if rep in dpor.explored:
                    continue
                dpor.explored.add(rep)
                dpor._explored_log.append(rep)
                dpor._explored_digests.add(prescription_digest(rep))
                dpor.frontier.append(rep)
                dpor._guides[rep] = np.asarray(guide, np.int32)
                dpor._class_of[rep] = k
                reseeded += 1
            stats["reseeded"] = reseeded
            stats["unseedable"] = unseedable
            stats["pending"] = pending_noted
            inherited_w = {}
            for code, w in led.witnesses.items():
                wk = w.get("class")
                # Inherit exactly the witnesses whose class TRANSFERRED
                # (same membership test as the split above, so a
                # transferred-but-not-re-executed witness is never
                # silently dropped); cone-witnessed codes re-execute
                # and must be re-found live.
                if wk is None or wk in cone_set:
                    continue
                inherited_w[int(code)] = w
            stats["inherited_codes"] = sorted(inherited_w)
            stats["inherited_witnesses"] = inherited_w

    stats["skipped_launches"] = stats["transferred"] // max(
        1, int(getattr(dpor, "batch_size", 1) or 1)
    )
    obs.journal.emit(
        "dpor.delta",
        **{k: v for k, v in stats.items() if k != "inherited_witnesses"},
    )
    return stats


def build_run_ledger(dpor, app, inherited: Optional[Dict[str, Any]] = None):
    """Assemble the enriched ``ClassLedger`` one finished exploration
    publishes: classes + meta (masks always, guides when the sleep set
    retained them), pending (admitted-never-executed) classes, the
    current app's effect manifest, and per-code canonical witnesses —
    merged with witnesses inherited from the warm source so a
    republished store keeps its history."""
    from ..fleet.ledger import ClassLedger, _better_witness

    sleep = dpor.sleep
    led = ClassLedger(sleep.classes, dpor.violation_codes)
    for k in led.classes:
        led.meta[k] = sleep.class_meta.get(k) or (
            class_tag_mask(k), -1, None, -1
        )
    pending_prescs = {
        tuple(tuple(int(x) for x in r) for r in p) for p in dpor.frontier
    }
    led.pending = {
        k for p, k in dpor._class_of.items() if p in pending_prescs
    }
    led.manifest = effect_manifest(app)
    for code, w in dpor.violation_witnesses.items():
        led.witnesses[int(code)] = dict(w)
    if inherited:
        for code, w in (inherited.get("inherited_witnesses") or {}).items():
            code = int(code)
            cur = led.witnesses.get(code)
            led.witnesses[code] = (
                dict(w) if cur is None else _better_witness(cur, dict(w))
            )
        led.violation_codes.update(
            int(c) for c in inherited.get("inherited_codes", ())
        )
    return led


def effective_violations(
    dpor, stats: Optional[Dict[str, Any]] = None
) -> Tuple[List[int], Dict[int, str]]:
    """The run's violation verdict with warm inheritance folded in:
    (sorted codes, per-code canonical witness sha). Live findings and
    inherited records merge by min digest — order-free, so a
    differential run and a scratch run of behavior-identical code
    produce the same verdict."""
    from ..fleet.ledger import _better_witness

    codes: Set[int] = {int(c) for c in dpor.violation_codes}
    wits: Dict[int, Dict[str, Any]] = {
        int(c): dict(w) for c, w in dpor.violation_witnesses.items()
    }
    if stats:
        codes.update(int(c) for c in stats.get("inherited_codes", ()))
        for code, w in (stats.get("inherited_witnesses") or {}).items():
            code = int(code)
            codes.add(code)
            cur = wits.get(code)
            wits[code] = dict(w) if cur is None else _better_witness(
                cur, dict(w)
            )
    return sorted(codes), {
        c: str(w.get("sha", "")) for c, w in sorted(wits.items())
    }
