"""Determinism-lint rule registry.

Every rule names one class of replay-breaker: a construct that makes an
actor handler's behavior depend on something the scheduler does not
control (wall clocks, process-global RNG, allocation addresses, hash
ordering, out-of-band I/O). The reference framework copes with these
AFTER the fact — wildcards and fungible clocks absorb nondeterministic
replays (SURVEY.md §5; DEMi's "shrinking" semantics) — while this linter
catches them BEFORE a soak spends hours recording schedules that will
never replay bit-exactly.

Severity contract:
  error   — replay/racing-analysis soundness is at risk; ``demi_tpu
            lint`` exits non-zero when any error-level finding survives
            suppression.
  warning — suspicious but not always wrong (e.g. iterating a set whose
            order never escapes the handler).
  info    — advisory.

Suppression: append ``# demi: allow(<rule-id>)`` to the flagged line or
to the enclosing ``def`` line (comma-separate several ids).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_RANK = {INFO: 0, WARNING: 1, ERROR: 2}


def severity_rank(severity: str) -> int:
    return _SEVERITY_RANK[severity]


@dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    summary: str
    hint: str


RULES: Dict[str, Rule] = {
    r.id: r
    for r in (
        Rule(
            "wall-clock",
            ERROR,
            "wall-clock read in a handler",
            "handlers must not read real time: model timing as "
            "scheduler-controlled timers (ctx.set_timer) so the explorer "
            "owns every clock",
        ),
        Rule(
            "unseeded-random",
            ERROR,
            "process-global / unseeded randomness in a handler",
            "draw from the harness instead (ctx.rng() is deterministic "
            "per delivery and replay-stable), or thread an explicitly "
            "seeded random.Random through the app",
        ),
        Rule(
            "id-ordering",
            ERROR,
            "id()-keyed ordering or keying",
            "id() is an allocation address — it differs across replays; "
            "key by a stable field of the object instead",
        ),
        Rule(
            "set-iteration",
            WARNING,
            "iteration-order-sensitive use of a set",
            "set iteration order depends on insertion/hash history; wrap "
            "in sorted(...) before iterating or serializing",
        ),
        Rule(
            "module-state",
            ERROR,
            "module-level mutable state written from a handler",
            "state shared across actors/executions breaks execution "
            "isolation (STS peek rollbacks cannot restore it); keep all "
            "state on the actor instance so checkpoint/restore sees it",
        ),
        Rule(
            "msg-mutation",
            ERROR,
            "in-place mutation of a received message",
            "messages are shared with the trace recorder and (under "
            "peek) with rolled-back executions; copy before mutating "
            "(the DEMI_SANITIZE=1 runtime digest check catches what "
            "this rule only suspects)",
        ),
        Rule(
            "thread-spawn",
            ERROR,
            "thread / task / process spawned inside a handler",
            "concurrency outside the controlled event loop is invisible "
            "to the scheduler; model it as actors + messages (the "
            "asyncio bridge adapters run coroutine apps under harness "
            "control)",
        ),
        Rule(
            "blocking-io",
            WARNING,
            "blocking I/O or sleep inside a handler",
            "handlers must be compute-only: I/O latency leaks real time "
            "into the schedule and sleeps stall the whole (sequential) "
            "event loop; route external effects through the bridge tier",
        ),
    )
}


def max_severity(findings) -> Tuple[int, int, int]:
    """(errors, warnings, infos) counts over an iterable of findings."""
    errors = warnings = infos = 0
    for f in findings:
        if f.severity == ERROR:
            errors += 1
        elif f.severity == WARNING:
            warnings += 1
        else:
            infos += 1
    return errors, warnings, infos
