"""StaticIndependence: the conservative may-commute relation DPOR consumes.

Two racing same-receiver deliveries can be skipped by the backtrack
derivation when the flip is provably a no-op:

  - **fungible** — the two records are content-identical in every column
    the prescribed-dispatch matcher consults (kind, receiver, payload,
    and sender for non-timers). Delivering either record prescribes the
    *same* lowest-seq pool entry, so the "flipped" prescription denotes
    the schedule the lane already executed — the identity flip. This is
    the static half of DEMi's wildcard/fungible-clock insight: identical
    messages are exchangeable. Sound for ANY handler.
  - **commute** — the static field-effect analysis (analysis/effects.py)
    proves the two message tags' handler effects commute on the receiver
    (disjoint read/write sets; |=-accumulations commute among
    themselves). Exported to the device tier as a fixed-shape boolean
    matrix so the batch-native scan (``demi_racing_prescriptions_static``)
    and the NumPy fallback consult it per round with no Python per-pair
    work.

Unsoundness is impossible by construction: an unanalyzable handler
yields UNKNOWN effects, UNKNOWN conflicts with everything, and the
fungible rule is handler-independent. The ``analysis.static_pruned``
counters (labels: kind=fungible|commute, tier=device|host) quantify the
schedule-space reduction next to the existing ``redundant`` /
``distance-pruned`` gauges; ``audit=True`` additionally materializes
every pruned prescription so the bench can assert that pruning removed
exactly the no-ops and nothing else.

Off by default everywhere: DeviceDPOR / DPORScheduler take
``static_independence=`` explicitly, or build one from the app under
``DEMI_STATIC_PRUNE=1`` / ``--static-prune``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .effects import (
    ActorEffects,
    AppEffects,
    analyze_actor_class,
    analyze_dsl_app,
    effects_commute,
)

REC_TIMER = 2  # device/core.py REC_TIMER (kept in sync by test_lint)


def static_prune_enabled(explicit: Optional[bool] = None) -> bool:
    """Resolve the static-pruning switch: explicit arg wins, else the
    ``DEMI_STATIC_PRUNE`` env flag. Off by default (every schedule-space
    feature in this repo ships opt-in with pinned parity)."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get("DEMI_STATIC_PRUNE", "").strip().lower() in (
        "1", "true", "yes", "on"
    )


class StaticIndependence:
    """May-commute relation over one app's message tags (device tier)
    and/or host actor classes, plus the fungible-flip rule.

    The object also carries the pruning ledger: ``pruned_total`` counts
    by kind, and (``audit=True``) ``pruned_prescriptions`` keeps every
    pruned prescription materialized for the bench/test no-op check."""

    def __init__(
        self,
        app_effects: Optional[AppEffects] = None,
        fungible: bool = True,
        audit: bool = False,
        actor_effects: Optional[Dict[str, ActorEffects]] = None,
    ):
        self.app_effects = app_effects
        self.fungible = bool(fungible)
        self.audit = bool(audit)
        self.actor_effects = actor_effects or {}
        self.pruned_total: Dict[str, int] = {"fungible": 0, "commute": 0}
        self.pruned_prescriptions: List[Tuple[Tuple[int, ...], ...]] = []
        self._matrix: Optional[np.ndarray] = None

    # -- constructors ------------------------------------------------------
    @classmethod
    def for_app(cls, app, fungible: bool = True, audit: bool = False
                ) -> "StaticIndependence":
        """Analyze a DSLApp's handler (analysis failure => a relation
        whose commute half declares nothing independent)."""
        return cls(
            app_effects=analyze_dsl_app(app), fungible=fungible, audit=audit
        )

    @classmethod
    def for_actor_classes(
        cls, classes: Dict[str, type], fungible: bool = True
    ) -> "StaticIndependence":
        """Host-tier relation over named Actor classes (keys are actor
        names or name prefixes; values are Actor subclasses)."""
        return cls(
            actor_effects={
                name: analyze_actor_class(c) for name, c in classes.items()
            },
            fungible=fungible,
        )

    # -- the relation ------------------------------------------------------
    def may_commute(self, tag1: int, tag2: int) -> bool:
        """Do deliveries of tags ``tag1`` and ``tag2`` to the same actor
        provably commute (DSL-app tier)? Unknown tags never commute."""
        eff = self.app_effects
        if eff is None:
            return False
        t1, t2 = int(tag1), int(tag2)
        if not (0 <= t1 <= eff.n_tags and 0 <= t2 <= eff.n_tags):
            return False
        return effects_commute(eff.effect_for(t1), eff.effect_for(t2))

    def device_matrix(self) -> Optional[np.ndarray]:
        """Fixed-shape uint8 [M, M] may-commute matrix over message tags
        (M = n_tags + 2; the last row/column is the catch-all for
        out-of-range tags and is all-False). None when no app analysis
        is attached — the scans then apply only the fungible rule."""
        if self.app_effects is None:
            return None
        if self._matrix is None:
            n = self.app_effects.n_tags
            m = n + 2
            mat = np.zeros((m, m), np.uint8)
            for a in range(0, n + 1):
                for b in range(a, n + 1):
                    if self.may_commute(a, b):
                        mat[a, b] = mat[b, a] = 1
            self._matrix = np.ascontiguousarray(mat)
        return self._matrix

    # -- per-pair predicates (legacy / host paths) ------------------------
    def pair_pruned_kind(
        self, row_i, row_j, rec_width: int
    ) -> Optional[str]:
        """'fungible' / 'commute' / None for one device-record racing
        pair — the scalar twin of the vectorized masks in
        native/analysis.py (fungible checked first; order is part of the
        counter contract)."""
        w = rec_width
        if self.fungible and _rows_fungible(row_i, row_j, w):
            return "fungible"
        mat = self.device_matrix()
        if mat is not None:
            m = len(mat)
            a, b = int(row_i[3]), int(row_j[3])
            ia = a if 0 <= a < m - 1 else m - 1
            ib = b if 0 <= b < m - 1 else m - 1
            if mat[ia, ib]:
                return "commute"
        return None

    def host_commutes_kind(self, ev_i, ev_j) -> Optional[str]:
        """'fungible' / 'commute' / None for a host-tier DporEvent pair
        (same receiver by construction of the racing scan)."""
        if self.fungible and (
            ev_i.fingerprint == ev_j.fingerprint
            and ev_i.is_timer == ev_j.is_timer
            and ev_i.rcv == ev_j.rcv
            and (ev_i.is_timer or ev_i.snd == ev_j.snd)
        ):
            return "fungible"
        if self.app_effects is not None:
            t1 = _fp_tag(ev_i.fingerprint)
            t2 = _fp_tag(ev_j.fingerprint)
            if t1 is not None and t2 is not None and self.may_commute(t1, t2):
                return "commute"
        if self.actor_effects:
            eff = self._actor_effects_for(ev_i.rcv)
            if eff is not None:
                e1 = eff.effect_for(_fp_type_key(ev_i.fingerprint))
                e2 = eff.effect_for(_fp_type_key(ev_j.fingerprint))
                if effects_commute(e1, e2):
                    return "commute"
        return None

    def _actor_effects_for(self, rcv: str) -> Optional[ActorEffects]:
        if rcv in self.actor_effects:
            return self.actor_effects[rcv]
        for prefix, eff in self.actor_effects.items():
            if rcv.startswith(prefix):
                return eff
        return None

    # -- pruning ledger ----------------------------------------------------
    def note_pruned(
        self, fungible: int = 0, commute: int = 0, tier: str = "device"
    ) -> None:
        """Fold one scan's prune counts into the ledger + obs counters."""
        from .. import obs

        if fungible:
            self.pruned_total["fungible"] += int(fungible)
            obs.counter("analysis.static_pruned").inc(
                int(fungible), kind="fungible", tier=tier
            )
        if commute:
            self.pruned_total["commute"] += int(commute)
            obs.counter("analysis.static_pruned").inc(
                int(commute), kind="commute", tier=tier
            )

    def note_pruned_prescription(
        self, prescription: Tuple[Tuple[int, ...], ...]
    ) -> None:
        if self.audit:
            self.pruned_prescriptions.append(prescription)

    @property
    def pruned(self) -> int:
        return sum(self.pruned_total.values())

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "fungible": self.fungible,
            "pruned": dict(self.pruned_total),
        }
        if self.app_effects is not None:
            pairs = []
            n = self.app_effects.n_tags
            for a in range(1, n + 1):
                for b in range(a, n + 1):
                    if self.may_commute(a, b):
                        pairs.append([a, b])
            out["commuting_tag_pairs"] = pairs
            out["analysis_failure"] = self.app_effects.failure
        return out


def _rows_fungible(row_i, row_j, w: int) -> bool:
    """Content-identity over the matchable columns of two device records:
    kind, dst, payload — and src only for non-timers (prescribed dispatch
    never matches a timer's src). parent/prev (the last two columns) are
    bookkeeping, not content."""
    if int(row_i[0]) != int(row_j[0]) or int(row_i[2]) != int(row_j[2]):
        return False
    for c in range(3, w - 2):
        if int(row_i[c]) != int(row_j[c]):
            return False
    return int(row_i[0]) == REC_TIMER or int(row_i[1]) == int(row_j[1])


def _fp_tag(fp) -> Optional[int]:
    """Message tag of a host-tier fingerprint: DSL messages fingerprint
    to their int tuples, whose first element is the tag."""
    if (
        isinstance(fp, tuple)
        and fp
        and isinstance(fp[0], int)
        and not isinstance(fp[0], bool)
    ):
        return fp[0]
    return None


def _fp_type_key(fp) -> Any:
    """Dispatch key of a host-tier fingerprint for Actor-class effects:
    the leading tag of tuple messages, or the dataclass/type name the
    BaseFingerprinter embeds."""
    if isinstance(fp, tuple) and fp:
        return fp[0]
    return fp
