"""Sleep sets & race-reversal bookkeeping: the optimal-DPOR half of
``demi_tpu/analysis`` (PR 8 built the static independence relation this
consumes).

Classic DPOR re-visits interleavings that differ only in already-reversed
races: two independent races reversed in either order reach the same
Mazurkiewicz class through tuple-distinct prescriptions, and a race
re-derived under a sibling's subtree re-enqueues a flip an earlier
sibling already explored. Parsimonious Optimal DPOR (arxiv 2405.11128)
eliminates both with sleep sets and wakeup trees; this module ports the
two mechanisms onto the repo's prescription-based frontier:

- **Sleep sets** (``SleepSets`` + the per-lane wake tracking in
  ``device/dpor_sweep.py``): when a reversal ``prefix + (f,)`` is
  admitted at a node, earlier-admitted sibling flips that are
  *independent* of ``f`` go to sleep in the new exploration — delivering
  them first would only commute into a sibling's already-scheduled
  subtree. Sleep rows ride the frontier as bounded packed int32 arrays
  (``[B, sleep_cap, rec_width]``); each device lane tracks, per sleeping
  row, the free-region delivery ordinal that woke it (a dependent or
  content-identical delivery) plus the first ordinal at which the lane
  itself delivered a still-sleeping row (the redundant-suffix marker).
  The racing scan then refuses reversals whose flip is asleep at the
  branch, and reversals branched beyond the redundant point.

- **Race-reversal (Mazurkiewicz class) dedup** (``canonical_class_key``):
  every admitted prescription is normalized to the lexicographically
  least linearization of its partial order — commuting adjacent records
  (different receivers, or tags the static matrix proves commuting, with
  creation edges kept) sort into a canonical order, and intra-
  prescription creation links are relabeled to canonical indices. Two
  reversal orders of independent races normalize to the SAME key, so the
  explored-set dedup — which only catches byte-equal prescriptions —
  is lifted to equivalence classes. The distinct-class count is also the
  per-fixture *optimal lower bound* the redundancy-ratio bench
  (``bench.py --config 9``) reports explored schedules against.

Soundness posture: pruning is conservative — unknown tags are dependent
(the PR 8 contract), creation edges always order, and a sleep row is
only consulted at branch points at/after the node it was attached to.
Everything is opt-in (``DEMI_SLEEP_SETS=1`` / ``--sleep-sets``) with the
unpruned path kept as the pinned A/B baseline; prune counts land in
``analysis.sleep_pruned{kind=sleep|class, tier=device|host}``.
"""

from __future__ import annotations

import heapq
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .independence import REC_TIMER, StaticIndependence, _rows_fungible

#: Wake/slept sentinel shared with the device kernels: "never" is any
#: ordinal >= BIG (int32-safe, far above any trace length).
BIG_ORDINAL = 2 ** 30

#: Own-position sentinel for rows whose trace position is unknown (seeded
#: prescriptions, flip rows): never equals a real parent column value, so
#: no creation edge can target such a row.
_POS_UNKNOWN = 1 << 40


def sleep_sets_enabled(explicit: Optional[bool] = None) -> bool:
    """Resolve the sleep-set switch: explicit arg wins, else the
    ``DEMI_SLEEP_SETS`` env flag. Off by default — like every
    schedule-space feature here, pruning ships opt-in with the unpruned
    path as the pinned A/B baseline."""
    if explicit is not None:
        return bool(explicit)
    return os.environ.get("DEMI_SLEEP_SETS", "").strip().lower() in (
        "1", "true", "yes", "on"
    )


def sleep_cap() -> int:
    """Bounded sleep-set width (rows per lane; fixed shape on device).
    Overflow drops the newest candidates — less pruning, never
    unsoundness."""
    return max(1, int(os.environ.get("DEMI_SLEEP_CAP", "8")))


def _tag_index(tag: int, m: int) -> int:
    return tag if 0 <= tag < m - 1 else m - 1


def rows_independent(row_a, row_b, rec_width: int, matrix=None) -> bool:
    """May two delivery records commute? Different receivers always do
    (handlers touch only their own actor's state; co-enabled rows cannot
    create each other); same-receiver pairs only when the static
    field-effect matrix proves their tags commute. Conservative in the
    PR 8 sense: no matrix => same receiver => dependent."""
    if int(row_a[2]) != int(row_b[2]):
        return True
    if matrix is not None:
        m = len(matrix)
        ia = _tag_index(int(row_a[3]), m)
        ib = _tag_index(int(row_b[3]), m)
        return bool(matrix[ia, ib])
    return False


def rows_content_equal(row_a, row_b, rec_width: int) -> bool:
    """Content identity over the matchable columns (the fungible-flip
    comparison: kind, dst, payload; src only for non-timers) — the ONE
    Python predicate, shared with the static-pruning tier so the
    native/vectorized mirrors have a single spec to match."""
    return _rows_fungible(row_a, row_b, rec_width)


def tag_bit(tag: int) -> int:
    """Bit position of one delivery tag in the compact per-class tag
    bitmask. Tags >= 63 saturate into bit 63 (several tags sharing a
    bit can only ENLARGE the apparent footprint — a transfer decision
    made on a saturated mask is conservative, never unsound)."""
    return 1 << min(max(int(tag), 0), 63)


#: Bit 62 of a reversal-chain mask (``class_meta`` field 3): a planted
#: trunk class — zero reversals, always re-executed on a differential
#: warm start so the shared prefix is revalidated for every transferred
#: descendant. Real delivery tags never reach bit 62 in practice; a tag
#: that saturates onto it could only force an extra re-execution
#: (conservative, never unsound).
TRUNK_BIT = 1 << 62


def guide_row_tag(row) -> int:
    """Delivery tag of one raw guide/trace record. Guide rows keep the
    device record layout ``(kind, src, dst, tag, ...)`` — tag at index
    3 — unlike canonical KEY rows ``(kind, dst, tag, ...)``."""
    return int(row[3]) if len(row) > 3 else 0


def class_tag_mask(key: tuple) -> int:
    """Delivery-tag footprint of one canonical class key as a 64-bit
    mask. Key rows are ``(kind, dst, tag, ...)`` (see
    ``canonical_class_key``: content index 2 is the record's tag), so
    the mask names exactly which handler tags the class's prescribed
    deliveries exercise — the admission-time evidence differential
    exploration (``analysis/delta.py``) tests against a change cone.
    The root class ``()`` has mask 0."""
    m = 0
    for row in key:
        if len(row) > 2:
            m |= tag_bit(row[2])
    return m


def canonical_class_key(
    rows, own_pos: Optional[Sequence[int]], rec_width: int, matrix=None
) -> tuple:
    """Mazurkiewicz-canonical key of one prescription.

    ``rows`` is the prescription's records ([m, >=rec_width] int-like);
    ``own_pos`` gives each row's own trace position in its source lane
    (None / ``_POS_UNKNOWN`` entries mean unknown — creation edges onto
    that row then never fire, which splits classes it could have merged:
    strictly less dedup, never a false merge). The key is the
    lexicographically least linearization of the prescription's partial
    order — ordering constraints are kept between every pair that is
    creation-linked (a row's ``parent`` column naming another row's
    trace position) or receiver-dependent (same ``dst`` and not proven
    commuting by ``matrix``) — with each row reduced to its matchable
    content plus its creation link relabeled to a canonical index.

    Two valid linearizations of the same partial order greedily
    topo-sort to the same minimal sequence, so equivalent reversal
    orders of independent races collide here even though their packed
    bytes differ."""
    rows = np.asarray(rows)[:, :rec_width].astype(np.int64, copy=False)
    m = len(rows)
    if m == 0:
        return ()
    w = rec_width
    if own_pos is None:
        pos = np.arange(m, dtype=np.int64) + _POS_UNKNOWN
    else:
        pos = np.asarray(
            [(_POS_UNKNOWN + k) if p is None else int(p)
             for k, p in enumerate(own_pos)],
            np.int64,
        )
    kind = rows[:, 0]
    dst = rows[:, 2]
    tag = rows[:, 3]
    src_eff = np.where(kind == REC_TIMER, 0, rows[:, 1])
    parent = rows[:, w - 2]
    content = [
        (int(kind[t]), int(dst[t]))
        + tuple(int(x) for x in rows[t, 3: w - 2])
        + (int(src_eff[t]),)
        for t in range(m)
    ]
    same_dst = dst[:, None] == dst[None, :]
    if matrix is not None:
        msz = len(matrix)
        idx = np.where((tag >= 0) & (tag < msz - 1), tag, msz - 1)
        comm = np.asarray(matrix)[idx[:, None], idx[None, :]].astype(bool)
        dep = same_dst & ~comm
    else:
        dep = same_dst
    creation = parent[None, :] == pos[:, None]  # [i, j]: i created j
    dep = dep | creation | creation.T
    order_lt = np.arange(m)[:, None] < np.arange(m)[None, :]
    edges = dep & order_lt  # i must precede j
    indeg = edges.sum(axis=0)
    heap = [(content[t], t) for t in range(m) if indeg[t] == 0]
    heapq.heapify(heap)
    order: List[int] = []
    while heap:
        _, t = heapq.heappop(heap)
        order.append(t)
        for u in np.flatnonzero(edges[t]):
            u = int(u)
            indeg[u] -= 1
            if indeg[u] == 0:
                heapq.heappush(heap, (content[u], u))
    new_index = {t: k for k, t in enumerate(order)}
    pos_to_new = {int(pos[t]): new_index[t] for t in range(m)}
    return tuple(
        content[t] + (pos_to_new.get(int(parent[t]), -1),)
        for t in order
    )


class SleepSets:
    """Sleep-set + class-dedup state for ONE exploration (a DeviceDPOR
    or DPORScheduler instance). DeviceDPOROracle builds one PER
    resumable instance — class/wakeup state is per-subsequence, so it
    refuses a shared instance — and aggregates the ledgers in its
    ``sleep_stats``.

    ``prune=False`` is OBSERVE mode: canonical classes are tracked (the
    redundancy-ratio denominator) but nothing is suppressed — the
    unpruned baseline of the bench A/B runs with this so both sides
    report explored-vs-classes on identical schedule spaces."""

    def __init__(
        self,
        independence: Optional[StaticIndependence] = None,
        cap: Optional[int] = None,
        prune: bool = True,
        audit: bool = False,
        retain_guides: bool = False,
    ):
        self.independence = independence
        self.matrix = (
            independence.device_matrix() if independence is not None else None
        )
        self.cap = sleep_cap() if cap is None else int(cap)
        self.prune = bool(prune)
        self.audit = bool(audit)
        # Store-backed runs keep each class's admission guide so a later
        # differential run can re-execute the class bit-identically
        # (content lane keys make the replay position-independent).
        # Off by default: plain explorations pay only the tag mask.
        self.retain_guides = bool(retain_guides)
        # Distinct Mazurkiewicz classes among admitted prescriptions —
        # the optimal-DPOR lower bound `bench --config 9` reports
        # explored counts against.
        self.classes: Set[tuple] = set()
        # Classes covered by a PRIOR run or another fleet host
        # (``seed_covered``): suppressed like any seen class, with the
        # skips counted separately in ``warm_hits`` — the warm-start
        # evidence `bench --config 13` asserts on.
        self.warm: Set[tuple] = set()
        self.warm_hits = 0
        # Per-class metadata: key -> (tag_mask, plen, guide_rows,
        # dmask). ``tag_mask`` is always present (one int per class —
        # the memory-parsimonious footprint record);
        # ``plen``/``guide_rows`` only when ``retain_guides`` (plen =
        # identity-prescription length; the identity prescription is
        # ``guide_rows[:plen]``), else ``(-1, None)``. ``dmask`` is the
        # reversal-chain tag mask: every explored class is the run's
        # seed trunk plus a chain of race reversals (one per ancestry
        # generation), and ``dmask`` ORs ``tag_bit`` of BOTH rows of
        # every reversed pair along that chain — recorded at admission,
        # when the pair is exact knowledge. ``TRUNK_BIT`` marks a
        # planted trunk class (zero reversals — it must always be
        # re-executed, revalidating the shared prefix for everyone
        # else); -1 means unknown lineage. Differential exploration
        # (analysis/delta.py) tests its change cone against ``dmask``.
        self.class_meta: Dict[
            tuple, Tuple[int, int, Optional[tuple], int]
        ] = {}
        self.pruned_total: Dict[str, int] = {"sleep": 0, "class": 0}
        self.pruned_prescriptions: List[Tuple[Tuple[int, ...], ...]] = []
        # Wakeup ledger: per branch node (exact prefix bytes), the flip
        # rows already admitted there — the "explored children" whose
        # independent successors sleep in later siblings.
        self._node_flips: Dict[bytes, List[Tuple[int, ...]]] = {}

    @classmethod
    def for_app(cls, app, **kw) -> "SleepSets":
        """Build with the app's static independence relation as the
        dependence oracle (analysis failure degrades to receiver-only
        dependence — less pruning, still sound)."""
        return cls(independence=StaticIndependence.for_app(app), **kw)

    # -- class dedup -------------------------------------------------------
    def class_key(
        self, rows, own_pos: Optional[Sequence[int]], rec_width: int
    ) -> tuple:
        return canonical_class_key(rows, own_pos, rec_width, self.matrix)

    def class_seen(self, key: tuple) -> bool:
        return key in self.classes

    def note_class(
        self,
        key: tuple,
        guide=None,
        plen: Optional[int] = None,
        dmask: Optional[int] = None,
    ) -> None:
        """Record one admitted class. ``guide``/``plen``/``dmask``
        (optional) are the admission's replay guide,
        identity-prescription length, and reversal-chain tag mask; they
        are retained only under ``retain_guides``. The class's
        delivery-tag mask is always derived from the key itself, so a
        stored mask can never disagree with the key it describes."""
        self.classes.add(key)
        cur = self.class_meta.get(key)
        if cur is not None and cur[2] is not None:
            return
        g: Optional[tuple] = None
        pl = -1
        dm = -1
        if self.retain_guides and guide is not None and plen is not None:
            g = tuple(
                tuple(int(x) for x in row) for row in np.asarray(guide)
            )
            pl = int(plen)
            if dmask is not None:
                dm = int(dmask)
        if cur is not None and g is None:
            return
        self.class_meta[key] = (class_tag_mask(key), pl, g, dm)

    def note_warm(self, key: tuple) -> None:
        """Count a class-dedup hit that was satisfied by warm-start
        coverage (a prior run / another host), not by this exploration's
        own admissions — the `fleet.warm_skips` evidence."""
        if key in self.warm:
            self.warm_hits += 1
            from .. import obs

            obs.counter("fleet.warm_skips").inc()

    # -- fleet export / merge ---------------------------------------------
    def export_classes(self) -> Dict[str, Any]:
        """Packed wire payload of the class ledger — sorted, delta-
        encoded zlib frames (the persist/ codec), the unit a fleet
        worker ships to the coordinator and the coordinator publishes
        to the content-addressed class store. Sorted order makes the
        bytes deterministic for a given class set, so equal ledgers
        produce equal content addresses."""
        from ..persist.checkpoint import pack_prescriptions

        return pack_prescriptions(sorted(self.classes))

    def merge_classes(self, payload) -> int:
        """Union class keys into this ledger (a packed payload from
        ``export_classes`` or an iterable of key tuples); returns how
        many were new. Set union is associative and commutative, so
        per-worker ledgers merge in any order or grouping to one answer
        — the fleet-aggregation contract tests/test_fleet.py pins."""
        if isinstance(payload, dict):
            from ..persist.checkpoint import unpack_prescriptions

            keys = unpack_prescriptions(payload)
        else:
            keys = list(payload)
        new = 0
        for k in keys:
            k = tuple(k)
            if k not in self.classes:
                self.classes.add(k)
                new += 1
        return new

    def seed_covered(self, payload, meta=None) -> int:
        """Warm start: merge ``payload`` AND mark those classes as
        covered by prior work — candidates in them are suppressed like
        any seen class, and each skip counts in ``warm_hits``.
        ``meta`` (optional ``key -> (mask, plen, guide, dmask)``) carries
        the stored per-class records forward so a re-publish keeps
        them."""
        if isinstance(payload, dict):
            from ..persist.checkpoint import unpack_prescriptions

            keys = [tuple(k) for k in unpack_prescriptions(payload)]
        else:
            keys = [tuple(k) for k in payload]
        self.warm.update(keys)
        new = self.merge_classes(keys)
        if meta:
            self.adopt_meta({k: meta[k] for k in keys if k in meta})
        return new

    def adopt_meta(
        self, meta: Dict[tuple, Tuple[int, int, Optional[tuple], int]]
    ) -> None:
        """Fold stored per-class metadata into this ledger (only for
        classes already present). A stored guide wins over a guide-less
        local record; an existing guide is kept."""
        for k, m in meta.items():
            if k not in self.classes:
                continue
            cur = self.class_meta.get(k)
            if cur is None or (cur[2] is None and m[2] is not None):
                dm = int(m[3]) if len(m) > 3 else -1
                self.class_meta[k] = (int(m[0]), int(m[1]), m[2], dm)

    # -- wakeup ledger / sleep assignment ---------------------------------
    def node_flips(self, node_key: bytes) -> List[Tuple[int, ...]]:
        return self._node_flips.get(node_key, [])

    def note_admitted_flip(self, node_key: bytes, flip: Tuple[int, ...]) -> None:
        self._node_flips.setdefault(node_key, []).append(tuple(flip))

    def child_sleep_rows(
        self,
        node_key: bytes,
        flip,
        rec_width: int,
        inherited: Sequence[Tuple[int, ...]] = (),
    ) -> Tuple[Tuple[int, ...], ...]:
        """Sleep rows for a freshly admitted ``prefix + (flip,)``:
        earlier-admitted sibling flips at the node plus the source
        lane's still-asleep rows, each kept only when independent of
        ``flip`` (delivering ``flip`` wakes its dependents — classic
        sleep-set inheritance), capped at ``cap`` (drop newest)."""
        out: List[Tuple[int, ...]] = []
        for row in list(self._node_flips.get(node_key, ())) + list(inherited):
            if len(out) >= self.cap:
                break
            if rows_independent(row, flip, rec_width, self.matrix):
                t = tuple(int(x) for x in row)
                if t not in out:
                    out.append(t)
        return tuple(out)

    # -- ledger ------------------------------------------------------------
    def note_pruned(
        self, sleep: int = 0, klass: int = 0, tier: str = "device"
    ) -> None:
        from .. import obs

        if sleep:
            self.pruned_total["sleep"] += int(sleep)
            obs.counter("analysis.sleep_pruned").inc(
                int(sleep), kind="sleep", tier=tier
            )
        if klass:
            self.pruned_total["class"] += int(klass)
            obs.counter("analysis.sleep_pruned").inc(
                int(klass), kind="class", tier=tier
            )

    def note_pruned_prescription(self, prescription) -> None:
        if self.audit:
            self.pruned_prescriptions.append(tuple(map(tuple, prescription)))

    @property
    def pruned(self) -> int:
        return sum(self.pruned_total.values())

    def redundancy_ratio(self, explored: int) -> Optional[float]:
        """Explored schedules over the distinct-class lower bound (>= 1;
        1.0 = optimal, every explored schedule its own class)."""
        if not self.classes:
            return None
        return explored / len(self.classes)

    def summary(self) -> Dict[str, Any]:
        return {
            "cap": self.cap,
            "prune": self.prune,
            "classes": len(self.classes),
            "pruned": dict(self.pruned_total),
        }


def np_wake_ordinals(
    deliveries: np.ndarray,
    sleep_from: int,
    sleep_rows: np.ndarray,
    rec_width: int,
    matrix=None,
) -> Tuple[np.ndarray, int]:
    """NumPy twin of the device kernel's per-lane wake tracking (the
    parity oracle for tests/test_sleep_sets.py): given one lane's
    delivered records in order (``deliveries`` [n, >=rec_width]), the
    lane's node ordinal ``sleep_from`` (tracking applies to deliveries
    at ordinals >= it), and the lane's sleep rows ([S, rec_width],
    kind 0 = empty slot), returns

      - ``wake``      [S] int64: first tracked delivery ordinal whose
        record is dependent with (or content-identical to) the sleeping
        row; ``BIG_ORDINAL`` if never;
      - ``slept_hit`` int: first tracked ordinal whose record is
        content-identical to a still-asleep row (the redundant-suffix
        marker); ``BIG_ORDINAL`` if never.
    """
    S = len(sleep_rows)
    wake = np.full(S, BIG_ORDINAL, np.int64)
    slept_hit = BIG_ORDINAL
    for o, row in enumerate(np.asarray(deliveries)):
        if o < sleep_from:
            continue
        hit = False
        for s in range(S):
            srow = sleep_rows[s]
            if int(srow[0]) == 0:
                continue
            asleep = wake[s] >= BIG_ORDINAL
            ceq = rows_content_equal(row, srow, rec_width)
            dep = ceq or not rows_independent(row, srow, rec_width, matrix)
            if asleep and ceq:
                hit = True
            if asleep and dep:
                wake[s] = o
        if hit and slept_hit >= BIG_ORDINAL:
            slept_hit = o
    return wake, slept_hit
