"""Determinism linter: an AST pass over actor handler functions.

Scope discovery (what counts as "handler code"):

  - every method of a class that looks like an actor — a base name
    containing ``Actor``, or a ``receive``/``handle`` method;
  - any function (at any nesting depth) named ``handler``, ``receive``,
    ``invariant``, ``init_state``, ``initial_msgs``, or ``on_*`` — the
    dual-tier DSL surface (apps are closures built inside ``make_*_app``
    factories, so nesting-blind discovery is what finds them).

Everything else in a module (CLI glue, fuzzer generators, bridge serve
loops) is deliberately out of scope: a seeded ``rng`` parameter in a
message generator is framework-sanctioned randomness, not a
replay-breaker.

Findings carry (rule id, severity, file:line, message, fix hint) and are
suppressible with ``# demi: allow(<rule-id>)`` on the flagged line or on
the enclosing ``def`` line. ``demi_tpu lint`` renders them as text or
JSON and exits non-zero on any error-level finding.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules import ERROR, RULES, WARNING, severity_rank

_ALLOW_RE = re.compile(r"#\s*demi:\s*allow\(([^)]*)\)")

# -- nondeterminism source tables -------------------------------------------

_WALL_CLOCK_CALLS = {
    ("time", "time"), ("time", "time_ns"), ("time", "monotonic"),
    ("time", "monotonic_ns"), ("time", "perf_counter"),
    ("time", "perf_counter_ns"), ("time", "process_time"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("date", "today"),
}

_RANDOM_MODULE_FNS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "getrandbits", "gauss", "normalvariate",
    "betavariate", "expovariate", "triangular", "vonmisesvariate",
    "paretovariate", "weibullvariate", "lognormvariate", "randbytes",
}

_UUID_FNS = {"uuid1", "uuid4"}

_THREAD_SPAWNS = {
    ("threading", "Thread"), ("threading", "Timer"),
    ("_thread", "start_new_thread"),
    ("multiprocessing", "Process"), ("multiprocessing", "Pool"),
    ("asyncio", "create_task"), ("asyncio", "ensure_future"),
    ("asyncio", "run"), ("asyncio", "get_event_loop"),
    ("asyncio", "new_event_loop"), ("asyncio", "run_coroutine_threadsafe"),
    ("concurrent", "ThreadPoolExecutor"),
    ("futures", "ThreadPoolExecutor"), ("futures", "ProcessPoolExecutor"),
}

_BLOCKING_CALLS = {
    ("time", "sleep"), ("socket", "socket"), ("socket", "create_connection"),
    ("subprocess", "run"), ("subprocess", "Popen"), ("subprocess", "call"),
    ("subprocess", "check_output"), ("subprocess", "check_call"),
    ("os", "system"), ("os", "popen"), ("requests", "get"),
    ("requests", "post"), ("requests", "put"), ("requests", "delete"),
    ("requests", "request"), ("urllib", "urlopen"), ("request", "urlopen"),
}

_BLOCKING_BARE = {"open", "input"}

_MUTATING_METHODS = {
    "append", "extend", "insert", "pop", "remove", "clear", "update",
    "setdefault", "popitem", "sort", "reverse", "add", "discard",
    "__setitem__",
}

_SET_CONSUMERS = {"list", "tuple", "join", "enumerate", "iter", "next", "zip"}

_HANDLER_FN_NAMES = {
    "handler", "receive", "invariant", "init_state", "initial_msgs",
}


@dataclass
class LintFinding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    hint: str
    handler: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> Dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "handler": self.handler,
        }


def _call_name(node: ast.expr) -> Tuple[Optional[str], Optional[str]]:
    """(base, attr) of a called name: ``time.time`` -> ('time', 'time'),
    bare ``open`` -> (None, 'open'), ``a.b.c()`` -> ('b', 'c')."""
    if isinstance(node, ast.Name):
        return None, node.id
    if isinstance(node, ast.Attribute):
        base = node.value
        if isinstance(base, ast.Name):
            return base.id, node.attr
        if isinstance(base, ast.Attribute):
            return base.attr, node.attr
        if isinstance(base, ast.Call):
            # datetime.datetime.now().timestamp() chains: report the
            # inner call separately; here just name the attr.
            return None, node.attr
    return None, None


def _is_handler_class(node: ast.ClassDef) -> bool:
    for base in node.bases:
        name = base.id if isinstance(base, ast.Name) else (
            base.attr if isinstance(base, ast.Attribute) else ""
        )
        if "Actor" in (name or ""):
            return True
    return any(
        isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        and item.name in ("receive", "handle")
        for item in node.body
    )


def _is_handler_fn(node) -> bool:
    return node.name in _HANDLER_FN_NAMES or node.name.startswith("on_")


def discover_handlers(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    """(qualified-name, def-node) handler roots, outermost-first with
    roots nested inside other roots removed (their subtree is already
    covered)."""
    roots: List[Tuple[str, ast.AST]] = []

    def walk(node: ast.AST, qual: str, inside_root: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                is_root = not inside_root and _is_handler_class(child)
                name = f"{qual}{child.name}"
                if is_root:
                    roots.append((name, child))
                walk(child, name + ".", inside_root or is_root)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                is_root = not inside_root and _is_handler_fn(child)
                name = f"{qual}{child.name}"
                if is_root:
                    roots.append((name, child))
                walk(child, name + ".", inside_root or is_root)
            else:
                walk(child, qual, inside_root)

    walk(tree, "", False)
    return roots


class _HandlerLinter(ast.NodeVisitor):
    """One handler root's rule pass. Collects raw findings; suppression
    is applied by the caller (it owns the source lines)."""

    def __init__(self, path: str, handler_name: str, root: ast.AST,
                 module_names: Set[str]):
        self.path = path
        self.handler_name = handler_name
        self.root = root
        self.module_names = module_names
        self.findings: List[LintFinding] = []
        # Message parameter names of enclosing handler defs (msg-mutation
        # targets): the canonical `msg`, plus the 4th positional of
        # receive(self, ctx, snd, msg) whatever it is called.
        self._msg_params: Set[str] = set()
        # Names bound to set values in this subtree (set-iteration).
        self._set_names: Set[str] = set()
        # def-line numbers (suppression may sit on the def line).
        self.def_lines: Dict[int, int] = {}

    # -- helpers -----------------------------------------------------------
    def _emit(self, rule_id: str, node: ast.AST, detail: str) -> None:
        rule = RULES[rule_id]
        self.findings.append(
            LintFinding(
                rule=rule.id, severity=rule.severity, path=self.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                message=f"{rule.summary}: {detail}",
                hint=rule.hint, handler=self.handler_name,
            )
        )

    def _is_set_expr(self, node: ast.expr) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            base, attr = _call_name(node.func)
            if base is None and attr in ("set", "frozenset"):
                return True
            if attr in ("keys", "values", "items") and isinstance(
                node.func, ast.Attribute
            ):
                return False  # dicts preserve insertion order
        if isinstance(node, ast.Name) and node.id in self._set_names:
            return True
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
        ):
            return self._is_set_expr(node.left) or self._is_set_expr(node.right)
        return False

    # -- visitors ----------------------------------------------------------
    def visit_FunctionDef(self, node) -> None:
        self.def_lines[node.lineno] = node.lineno
        args = node.args.posonlyargs + node.args.args
        names = [a.arg for a in args]
        if "msg" in names:
            self._msg_params.add("msg")
        if node.name == "receive" and len(names) >= 4 and names[0] == "self":
            self._msg_params.add(names[3])
        if node.name == "handle" and len(names) >= 4 and names[0] == "self":
            self._msg_params.add(names[3])
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Global(self, node: ast.Global) -> None:
        self._emit(
            "module-state", node,
            f"`global {', '.join(node.names)}` inside a handler",
        )

    def visit_Call(self, node: ast.Call) -> None:
        base, attr = _call_name(node.func)
        key = (base, attr)
        # numpy's module-level RNG parses to base='random' (the middle
        # attr of np.random.<fn>); detect the full chain up front so it
        # reports once, under its real name.
        np_random = (
            isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "random"
            and isinstance(node.func.value.value, ast.Name)
            and node.func.value.value.id in ("np", "numpy")
        )
        if key in _WALL_CLOCK_CALLS:
            self._emit("wall-clock", node, f"{base}.{attr}()")
        elif np_random:
            self._emit("unseeded-random", node, f"np.random.{attr}()")
        elif base == "random" and attr in _RANDOM_MODULE_FNS:
            self._emit("unseeded-random", node, f"{base}.{attr}()")
        elif base == "uuid" and attr in _UUID_FNS:
            self._emit("unseeded-random", node, f"uuid.{attr}()")
        elif base == "os" and attr == "urandom":
            self._emit("unseeded-random", node, "os.urandom()")
        elif base == "secrets":
            self._emit("unseeded-random", node, f"secrets.{attr}()")
        elif base == "np.random" or (
            base == "random" and attr == "default_rng"
        ):
            self._emit("unseeded-random", node, f"{base}.{attr}()")
        elif key in _THREAD_SPAWNS or attr in (
            "create_task", "ensure_future", "call_later", "call_soon",
            "run_in_executor", "start_new_thread",
        ) and base not in (None, "ctx"):
            self._emit("thread-spawn", node, f"{base}.{attr}()")
        elif key in _BLOCKING_CALLS:
            self._emit("blocking-io", node, f"{base}.{attr}()")
        elif base is None and attr in _BLOCKING_BARE:
            self._emit("blocking-io", node, f"{attr}()")
        elif base is None and attr in ("sorted", "min", "max"):
            self._check_ordering_key(node)

        # Mutating method on a received message object.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATING_METHODS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in self._msg_params
        ):
            self._emit(
                "msg-mutation", node,
                f"{node.func.value.id}.{node.func.attr}(...)",
            )

        # Mutating method on module-level mutable state.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATING_METHODS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in self.module_names
        ):
            self._emit(
                "module-state", node,
                f"{node.func.value.id}.{node.func.attr}(...) mutates "
                "module-level state",
            )

        # Iteration-order-sensitive consumption of a set.
        if base is None and attr in _SET_CONSUMERS and node.args:
            if self._is_set_expr(node.args[0]):
                self._emit(
                    "set-iteration", node, f"{attr}(<set>) without sorted()"
                )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and node.args
            and self._is_set_expr(node.args[0])
        ):
            self._emit("set-iteration", node, "str.join over a set")

        self.generic_visit(node)

    def _check_ordering_key(self, node: ast.Call) -> None:
        """sorted/min/max with a key (or elements) that call id()."""
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "id"
            ):
                self._emit(
                    "id-ordering", sub,
                    "id() inside an ordering expression",
                )
                return

    def visit_For(self, node: ast.For) -> None:
        if self._is_set_expr(node.iter):
            self._emit("set-iteration", node, "for-loop over a set")
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._is_set_expr(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._set_names.add(tgt.id)
        for tgt in node.targets:
            self._check_store(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._check_store(tgt)
        self.generic_visit(node)

    def _check_store(self, tgt: ast.expr) -> None:
        """Subscript/attribute stores onto received messages or
        module-level names."""
        if isinstance(tgt, (ast.Subscript, ast.Attribute)):
            base = tgt.value
            if isinstance(base, ast.Name):
                if base.id in self._msg_params:
                    self._emit(
                        "msg-mutation", tgt,
                        f"store into received message `{base.id}`",
                    )
                elif base.id in self.module_names:
                    self._emit(
                        "module-state", tgt,
                        f"store into module-level `{base.id}`",
                    )
        elif isinstance(tgt, ast.Name) and tgt.id in self.module_names:
            # Plain rebinding of a module-level name only matters with
            # `global`, which visit_Global already flags.
            pass


def _module_level_mutables(tree: ast.Module) -> Set[str]:
    """Names assigned mutable-looking values at module scope (the
    module-state rule's write targets)."""
    out: Set[str] = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        mutable = isinstance(
            value, (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp,
                    ast.SetComp)
        ) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("dict", "list", "set", "defaultdict",
                                  "OrderedDict", "deque", "Counter")
        )
        if not mutable:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
    return out


def _allowed_rules(line: str) -> Set[str]:
    m = _ALLOW_RE.search(line)
    if not m:
        return set()
    return {part.strip() for part in m.group(1).split(",") if part.strip()}


def lint_source(
    source: str, path: str = "<string>"
) -> List[LintFinding]:
    """Lint one module's source text. Returns surviving findings
    (suppressions already applied), sorted by (line, rule)."""
    tree = ast.parse(source)
    lines = source.splitlines()
    module_names = _module_level_mutables(tree)
    findings: List[LintFinding] = []
    for name, root in discover_handlers(tree):
        linter = _HandlerLinter(path, name, root, module_names)
        linter.visit(root)
        findings.extend(linter.findings)

    # Suppression: `# demi: allow(rule)` on the flagged line or on the
    # enclosing def line (nearest def at or above the finding).
    def_lines = sorted(
        node.lineno
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    )

    def suppressed(f: LintFinding) -> bool:
        if 0 < f.line <= len(lines) and f.rule in _allowed_rules(
            lines[f.line - 1]
        ):
            return True
        enclosing = [ln for ln in def_lines if ln <= f.line]
        if enclosing and 0 < enclosing[-1] <= len(lines):
            return f.rule in _allowed_rules(lines[enclosing[-1] - 1])
        return False

    out = [f for f in findings if not suppressed(f)]
    out.sort(key=lambda f: (f.line, f.rule))
    return out


def lint_file(path: str) -> List[LintFinding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def _module_files(name: str) -> List[str]:
    """Resolve a dotted module/package name to .py files WITHOUT
    importing it (linting must not execute target code)."""
    spec = importlib.util.find_spec(name)
    if spec is None or spec.origin is None:
        raise FileNotFoundError(f"cannot resolve module {name!r}")
    if spec.submodule_search_locations:
        files = []
        for loc in spec.submodule_search_locations:
            for fn in sorted(os.listdir(loc)):
                if fn.endswith(".py"):
                    files.append(os.path.join(loc, fn))
        return files
    return [spec.origin]


DEFAULT_TARGETS = ("demi_tpu.apps", "demi_tpu.bridge.demo_app")


def lint_targets(
    targets: Optional[Sequence[str]] = None,
) -> List[LintFinding]:
    """Lint files, directories, or dotted module names. With no targets,
    lints the bundled app zoo (the shipped-clean baseline)."""
    targets = list(targets) if targets else list(DEFAULT_TARGETS)
    files: List[str] = []
    for t in targets:
        if os.path.isdir(t):
            for root, _dirs, names in os.walk(t):
                files.extend(
                    os.path.join(root, n) for n in sorted(names)
                    if n.endswith(".py")
                )
        elif os.path.isfile(t):
            files.append(t)
        else:
            files.extend(_module_files(t))
    findings: List[LintFinding] = []
    for path in files:
        findings.extend(lint_file(path))
    return findings


def render_text(findings: Sequence[LintFinding]) -> str:
    if not findings:
        return "clean: no findings\n"
    lines = []
    for f in findings:
        lines.append(
            f"{f.path}:{f.line}:{f.col}: {f.severity} [{f.rule}] "
            f"{f.message}"
        )
        lines.append(f"    hint: {f.hint}")
        if f.handler:
            lines.append(f"    in: {f.handler}")
    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = sum(1 for f in findings if f.severity == WARNING)
    lines.append(f"{len(findings)} finding(s): {errors} error(s), "
                 f"{warnings} warning(s)")
    return "\n".join(lines) + "\n"


def render_json(findings: Sequence[LintFinding]) -> Dict:
    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = sum(1 for f in findings if f.severity == WARNING)
    return {
        "findings": [f.to_json() for f in findings],
        "counts": {
            "total": len(findings),
            "error": errors,
            "warning": warnings,
            "info": len(findings) - errors - warnings,
        },
    }


def has_errors(findings: Sequence[LintFinding]) -> bool:
    return any(
        severity_rank(f.severity) >= severity_rank(ERROR) for f in findings
    )
