"""Static analysis subsystem: determinism lint, static commutativity,
and the runtime replay sanitizer.

Three coupled passes over app code (the bundled zoo, bridge apps, and
arbitrary user modules):

  1. ``lint`` — an AST pass over actor handler functions flagging
     replay-breakers (wall clocks, unseeded randomness, id()-keyed
     ordering, set-iteration order, module-level mutable state, in-place
     message mutation, thread spawning, blocking I/O), suppressible via
     ``# demi: allow(<rule>)``. CLI: ``demi_tpu lint``.
  2. ``effects`` / ``independence`` — per-(actor, message-tag)
     read/write field-set extraction from handler ASTs, composed into
     the conservative ``StaticIndependence`` may-commute relation that
     DeviceDPOR, the host DPORScheduler, and the batch-native racing
     scan consume to skip provably-no-op racing pairs
     (``analysis.static_pruned`` counters; ``DEMI_STATIC_PRUNE=1``).
  3. ``sanitize`` — the ``DEMI_SANITIZE=1`` runtime sanitizer wrapping
     handler dispatch: message digests before/after delivery catch the
     in-place mutation the lint only suspects; time/random traps reject
     nondeterminism during strict replay.
"""

from .delta import (
    DeltaPlan,
    build_run_ledger,
    compute_delta,
    delta_warm_start,
    effect_manifest,
    effective_violations,
    split_transfer,
)
from .effects import (
    ActorEffects,
    AppEffects,
    EffectSet,
    analyze_actor_class,
    analyze_dsl_app,
    effects_commute,
    fn_digest,
)
from .independence import StaticIndependence, static_prune_enabled
from .sleep import (
    BIG_ORDINAL,
    SleepSets,
    canonical_class_key,
    class_tag_mask,
    np_wake_ordinals,
    rows_content_equal,
    rows_independent,
    sleep_cap,
    sleep_sets_enabled,
    tag_bit,
)
from .lint import (
    DEFAULT_TARGETS,
    LintFinding,
    has_errors,
    lint_file,
    lint_source,
    lint_targets,
    render_json,
    render_text,
)
from .rules import RULES
from . import sanitize

__all__ = [
    "ActorEffects",
    "AppEffects",
    "BIG_ORDINAL",
    "DEFAULT_TARGETS",
    "DeltaPlan",
    "EffectSet",
    "LintFinding",
    "RULES",
    "SleepSets",
    "StaticIndependence",
    "build_run_ledger",
    "canonical_class_key",
    "class_tag_mask",
    "compute_delta",
    "delta_warm_start",
    "effect_manifest",
    "effective_violations",
    "fn_digest",
    "split_transfer",
    "tag_bit",
    "np_wake_ordinals",
    "rows_content_equal",
    "rows_independent",
    "sleep_cap",
    "sleep_sets_enabled",
    "analyze_actor_class",
    "analyze_dsl_app",
    "effects_commute",
    "has_errors",
    "lint_file",
    "lint_source",
    "lint_targets",
    "render_json",
    "render_text",
    "sanitize",
    "static_prune_enabled",
]
