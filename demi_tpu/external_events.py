"""User-facing external event vocabulary: the fault/input language.

Reference: src/main/scala/verification/ExternalEvents.scala (202 LoC).
External events are what the fuzzer generates and what DDMin minimizes.
Each instance carries a unique ``eid`` (reference: UniqueExternalEvent,
ExternalEvents.scala:14-31) so that structurally-equal events at different
trace positions stay distinguishable across subsequence trials.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence


class _EidCounter:
    def __init__(self):
        self._next = 1

    def next(self) -> int:
        value = self._next
        self._next += 1
        return value

    def ensure_floor(self, floor: int) -> None:
        """Advance past ``floor`` — deserialization restores recorded eids
        and must keep fresh events from aliasing them (eids are identity)."""
        if self._next <= floor:
            self._next = floor + 1


_eid_counter = _EidCounter()


def _next_eid() -> int:
    return _eid_counter.next()


def ensure_eid_floor(floor: int) -> None:
    _eid_counter.ensure_floor(floor)


class MessageConstructor:
    """Late-bound constructor for externally injected messages.

    Reference: ExternalMessageConstructor (ExternalEvents.scala:43-55). Late
    binding lets replays rebuild messages that close over live actor handles,
    and ``mask_components`` supports payload shrinking
    (RunnerUtils.shrinkSendContents, RunnerUtils.scala:1007-1094): a
    constructor may expose sub-components (e.g. a membership list) that the
    minimizer can mask out one at a time.
    """

    def __init__(self, fn: Callable[[], Any], components: Optional[Sequence[Any]] = None):
        self._fn = fn
        self._components = list(components) if components is not None else []
        self._masked: frozenset = frozenset()

    def __call__(self) -> Any:
        return self.construct()

    def construct(self) -> Any:
        if self._masked and self._components:
            return self._fn_with_mask()
        return self._fn()

    # -- shrinking support -------------------------------------------------
    @property
    def components(self) -> List[Any]:
        return list(self._components)

    def masked(self, masked_indices) -> "MessageConstructor":
        clone = MessageConstructor(self._fn, self._components)
        clone._masked = frozenset(masked_indices)
        return clone

    def _fn_with_mask(self):
        kept = [c for i, c in enumerate(self._components) if i not in self._masked]
        return self._fn(kept) if _accepts_arg(self._fn) else self._fn()

    def __repr__(self):
        return f"MessageConstructor(masked={sorted(self._masked)})"


def _accepts_arg(fn) -> bool:
    try:
        import inspect

        sig = inspect.signature(fn)
        return len(sig.parameters) >= 1
    except (TypeError, ValueError):
        return False


def constant_message(msg: Any) -> MessageConstructor:
    return MessageConstructor(lambda: msg)


@dataclass(frozen=True, eq=False)
class ExternalEvent:
    """Base class. Identity (eid) equality: minimization must distinguish
    equal-looking events at different positions."""

    eid: int = field(default_factory=_next_eid, init=False)
    # External atomic block membership (reference:
    # ExternalEventInjector.scala:179-216 begin/endExternalAtomicBlock):
    # consecutive events sharing a block id inject as one atomic batch
    # (Begin/End markers recorded around them), minimize as ONE atom
    # (all-or-nothing, never interleaved), and replay unignorably. Assign
    # via ``atomic_block(...)``.
    block_id: Optional[int] = field(default=None, init=False, compare=False)

    # Identity semantics but stable hashing across pickling.
    def __eq__(self, other):
        return isinstance(other, ExternalEvent) and self.eid == other.eid

    def __hash__(self):
        return hash(self.eid)

    @property
    def label(self) -> str:
        return f"e{self.eid}"


@dataclass(frozen=True, eq=False)
class Start(ExternalEvent):
    """Spawn (or respawn, re-enabling traffic) an actor by name.

    Reference: ExternalEvents.scala Start(propCtor, name); a later Start for
    a previously Killed name acts as recovery (EventOrchestrator.trigger_start).
    """

    name: str = ""
    ctor: Optional[Callable[[], Any]] = field(default=None, compare=False, repr=False)


@dataclass(frozen=True, eq=False)
class Kill(ExternalEvent):
    """Isolate an actor: all of its traffic is dropped, but it is not stopped
    (reference semantics: Kill = isolation, EventOrchestrator.scala:51-59)."""

    name: str = ""


@dataclass(frozen=True, eq=False)
class HardKill(ExternalEvent):
    """Actually stop the actor and scrub its pending state
    (reference: EventOrchestrator.trigger_hard_kill:243-312)."""

    name: str = ""


@dataclass(frozen=True, eq=False)
class Send(ExternalEvent):
    name: str = ""
    msg_ctor: MessageConstructor = field(default=None, compare=False, repr=False)

    def message(self) -> Any:
        return self.msg_ctor.construct()


@dataclass(frozen=True, eq=False)
class WaitQuiescence(ExternalEvent):
    """Block injection until no deliverable messages remain.

    ``budget`` bounds the wait: advance after quiescence OR after that many
    deliveries in the segment, whichever first. Timer-driven apps (Raft
    elections re-arm forever) never truly quiesce — the reference copes by
    capping whole runs (RandomScheduler.setMaxMessages,
    RandomScheduler.scala:54-57); a per-segment budget keeps multi-phase
    programs progressing instead. None = strict quiescence; budget must be
    >= 1 (0 would mean opposite things on the two tiers)."""

    budget: Optional[int] = None

    def __post_init__(self):
        if self.budget is not None and self.budget < 1:
            raise ValueError("WaitQuiescence budget must be None or >= 1")


@dataclass(frozen=True, eq=False)
class WaitCondition(ExternalEvent):
    """Block injection until a condition holds
    (reference: ExternalEventInjector.scala:541-580 re-arm semantics).

    Two forms: ``cond`` — an arbitrary zero-arg host closure (host-tier
    only, like the reference's); ``cond_id`` — an index into the app's
    ``DSLApp.conditions`` jax predicates, usable on BOTH tiers (the
    device kernels end the dispatch segment when the predicate holds).
    ``budget`` optionally bounds the wait in deliveries, like
    WaitQuiescence."""

    cond: Callable[[], bool] = field(default=None, compare=False, repr=False)
    cond_id: Optional[int] = None
    budget: Optional[int] = None


@dataclass(frozen=True, eq=False)
class Partition(ExternalEvent):
    a: str = ""
    b: str = ""


@dataclass(frozen=True, eq=False)
class UnPartition(ExternalEvent):
    a: str = ""
    b: str = ""


@dataclass(frozen=True, eq=False)
class CodeBlock(ExternalEvent):
    """Run an arbitrary host-side block atomically at this point."""

    block: Callable[[], None] = field(default=None, compare=False, repr=False)
    label: str = ""


def externals_summary(events: Sequence[ExternalEvent]) -> str:
    parts = []
    for e in events:
        if isinstance(e, Start):
            parts.append(f"Start({e.name})")
        elif isinstance(e, Kill):
            parts.append(f"Kill({e.name})")
        elif isinstance(e, HardKill):
            parts.append(f"HardKill({e.name})")
        elif isinstance(e, Send):
            parts.append(f"Send({e.name})")
        elif isinstance(e, WaitQuiescence):
            parts.append("WaitQuiescence")
        elif isinstance(e, WaitCondition):
            parts.append("WaitCondition")
        elif isinstance(e, Partition):
            parts.append(f"Partition({e.a},{e.b})")
        elif isinstance(e, UnPartition):
            parts.append(f"UnPartition({e.a},{e.b})")
        elif isinstance(e, CodeBlock):
            parts.append(f"CodeBlock({e.label})")
        else:
            parts.append(type(e).__name__)
    return " ".join(parts)


def atomic_block(
    events: Sequence[ExternalEvent], block_id: Optional[int] = None
) -> List[ExternalEvent]:
    """Mark ``events`` as one external atomic block (reference:
    beginExternalAtomicBlock / endExternalAtomicBlock,
    ExternalEventInjector.scala:179-216 — the mechanism a nondeterministic
    external client uses to mark 'this batch is one logical input'):

      - injection applies the members back-to-back with Begin/End markers
        recorded around them (schedulers/base.py);
      - DDMin removes the block all-or-nothing and never interleaves
        other events into it (minimization/event_dag.py atomize);
      - STS replay treats the block's recorded consequences as
        unignorable — absences inside it raise instead of being skipped
        (schedulers/replay.py), the sequential-world rendering of the
        reference's 'wait for block end before deciding whether its
        messages show up' (STSScheduler.scala:414-444).

    Returns the same event objects (mutated in place: block ids ride the
    eid counter so deserialization can floor past them). Members must be
    used contiguously and must not contain Wait* events."""
    events = list(events)
    bid = block_id if block_id is not None else _next_eid()
    for e in events:
        if isinstance(e, (WaitQuiescence, WaitCondition)):
            raise ValueError(f"atomic blocks cannot contain waits: {e!r}")
        object.__setattr__(e, "block_id", bid)
    return events


def sanity_check_externals(events: Sequence[ExternalEvent]) -> None:
    """Reject trivially malformed fuzz tests: sends/kills of never-started
    actors (reference: Fuzzer.validateFuzzTest, Fuzzer.scala:126-133) and
    non-contiguous atomic blocks."""
    started = set()
    closed_blocks = set()
    open_block: Optional[int] = None
    for e in events:
        if e.block_id != open_block:
            if open_block is not None:
                closed_blocks.add(open_block)
            if e.block_id in closed_blocks:
                raise ValueError(
                    f"atomic block {e.block_id} is not contiguous at {e}"
                )
            open_block = e.block_id
        if isinstance(e, Start):
            started.add(e.name)
        elif isinstance(e, (Kill, HardKill)):
            if e.name not in started:
                raise ValueError(f"{e} targets never-started actor {e.name}")
        elif isinstance(e, Send):
            if e.name not in started:
                raise ValueError(f"{e} targets never-started actor {e.name}")
        elif isinstance(e, (WaitQuiescence, WaitCondition)):
            if e.block_id is not None:
                raise ValueError(f"atomic blocks cannot contain waits: {e!r}")
