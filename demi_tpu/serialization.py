"""Experiment persistence: save/restore fuzz+minimization artifacts.

Reference: verification/Serialization.scala (526 LoC). The reference uses
Java serialization with heavy sanitization (closures → fingerprints,
ActorRefs re-resolved by re-booting a system, Serialization.scala:124-155).
Here everything is *structural JSON*: DSL messages are int tuples, external
events serialize as records, and deserialization rebuilds constructors from
the app definition — no code objects on disk, diffable experiment dirs.

Layout of an experiment dir (reference files in parens):
  metadata.json             (lifecycle.py capture)
  externals.json            (original_externals.bin)
  event_trace.json          (event_trace.bin)
  violation.json            (violation.bin)
  mcs.json                  (mcs.bin)                [optional]
  minimized_trace.json      (minimizedInternalTrace.bin) [optional]
  minimization_stats.json   (minimization_stats.json)   [optional]
"""

from __future__ import annotations

import json
import os
import platform
import subprocess
import time
from typing import Any, Dict, List, Optional, Sequence

from .dsl import DSLApp
from .events import (
    WildCardMatch,
    BeginExternalAtomicBlock,
    BeginUnignorableEvents,
    BeginWaitCondition,
    BeginWaitQuiescence,
    CodeBlockEvent,
    EndExternalAtomicBlock,
    EndUnignorableEvents,
    Event,
    HardKillEvent,
    KillEvent,
    MsgEvent,
    MsgSend,
    PartitionEvent,
    Quiescence,
    SpawnEvent,
    TimerDelivery,
    UnPartitionEvent,
    Unique,
)
from .external_events import (
    ExternalEvent,
    HardKill,
    Kill,
    MessageConstructor,
    Partition,
    Send,
    Start,
    UnPartition,
    WaitCondition,
    WaitQuiescence,
    ensure_eid_floor,
)
from .minimization.stats import MinimizationStats
from .minimization.test_oracle import IntViolation
from .runtime.actor import dsl_actor_factory
from .trace import EventTrace

_EVENT_TYPES = {
    "msg_send": MsgSend,
    "msg_event": MsgEvent,
    "timer_delivery": TimerDelivery,
    "spawn": SpawnEvent,
    "kill": KillEvent,
    "hardkill": HardKillEvent,
    "partition": PartitionEvent,
    "unpartition": UnPartitionEvent,
    "quiescence": Quiescence,
    "begin_wait_quiescence": BeginWaitQuiescence,
    "begin_wait_condition": BeginWaitCondition,
    "begin_unignorable": BeginUnignorableEvents,
    "end_unignorable": EndUnignorableEvents,
    "code_block": CodeBlockEvent,
}


def _msg_to_json(msg: Any):
    if isinstance(msg, WildCardMatch):
        # Wildcarded expected deliveries occur in minimization-stage
        # checkpoints (policy enum only; closure selectors don't persist,
        # matching the reference's sanitization).
        return {"t": "wc", "tag": msg.class_tag, "policy": msg.policy}
    if isinstance(msg, tuple):
        return {"t": "tuple", "v": list(int(x) for x in msg)}
    if isinstance(msg, (int, str, float, bool)) or msg is None:
        return {"t": "lit", "v": msg}
    return {"t": "repr", "v": repr(msg)}


def _msg_from_json(obj):
    if obj["t"] == "wc":
        return WildCardMatch(class_tag=obj["tag"], policy=obj["policy"])
    if obj["t"] == "tuple":
        return tuple(obj["v"])
    return obj["v"]


def _fp_to_json(fp: Any):
    """Fingerprints are nested tuples/scalars; JSON lists don't round-trip
    to tuples, so encode structure explicitly."""
    if isinstance(fp, tuple):
        return {"t": "tuple", "v": [_fp_to_json(x) for x in fp]}
    return {"t": "lit", "v": fp}


def _fp_from_json(obj) -> Any:
    if obj["t"] == "tuple":
        return tuple(_fp_from_json(x) for x in obj["v"])
    return obj["v"]


def _event_to_json(u: Unique) -> Dict[str, Any]:
    e = u.event
    rec: Dict[str, Any] = {"id": u.id}
    if isinstance(e, MsgSend):
        rec.update(type="msg_send", snd=e.snd, rcv=e.rcv, msg=_msg_to_json(e.msg))
    elif isinstance(e, MsgEvent):
        rec.update(type="msg_event", snd=e.snd, rcv=e.rcv, msg=_msg_to_json(e.msg))
    elif isinstance(e, TimerDelivery):
        rec.update(type="timer_delivery", rcv=e.rcv, msg=_msg_to_json(e.msg))
    elif isinstance(e, SpawnEvent):
        rec.update(type="spawn", name=e.name)
    elif isinstance(e, KillEvent):
        rec.update(type="kill", name=e.name)
    elif isinstance(e, HardKillEvent):
        rec.update(type="hardkill", name=e.name)
    elif isinstance(e, PartitionEvent):
        rec.update(type="partition", a=e.a, b=e.b)
    elif isinstance(e, UnPartitionEvent):
        rec.update(type="unpartition", a=e.a, b=e.b)
    elif isinstance(e, CodeBlockEvent):
        rec.update(type="code_block", label=e.label)
    elif isinstance(e, Quiescence):
        rec.update(type="quiescence")
    elif isinstance(e, BeginWaitQuiescence):
        rec.update(type="begin_wait_quiescence")
    elif isinstance(e, BeginWaitCondition):
        rec.update(type="begin_wait_condition")
    elif isinstance(e, BeginUnignorableEvents):
        rec.update(type="begin_unignorable")
    elif isinstance(e, EndUnignorableEvents):
        rec.update(type="end_unignorable")
    elif isinstance(e, BeginExternalAtomicBlock):
        rec.update(type="begin_atomic", block=e.block_id)
    elif isinstance(e, EndExternalAtomicBlock):
        rec.update(type="end_atomic", block=e.block_id)
    else:
        raise TypeError(f"unserializable event {e!r}")
    return rec


def _event_from_json(rec: Dict[str, Any], app: Optional[DSLApp]) -> Unique:
    t = rec["type"]
    if t == "msg_send":
        e: Event = MsgSend(rec["snd"], rec["rcv"], _msg_from_json(rec["msg"]))
    elif t == "msg_event":
        e = MsgEvent(rec["snd"], rec["rcv"], _msg_from_json(rec["msg"]))
    elif t == "timer_delivery":
        e = TimerDelivery(rec["rcv"], _msg_from_json(rec["msg"]))
    elif t == "spawn":
        ctor = None
        if app is not None:
            ctor = dsl_actor_factory(app, app.actor_id(rec["name"]))
        e = SpawnEvent("__external__", rec["name"], ctor=ctor)
    elif t == "kill":
        e = KillEvent(rec["name"])
    elif t == "hardkill":
        e = HardKillEvent(rec["name"])
    elif t == "partition":
        e = PartitionEvent(rec["a"], rec["b"])
    elif t == "unpartition":
        e = UnPartitionEvent(rec["a"], rec["b"])
    elif t == "code_block":
        e = CodeBlockEvent(rec.get("label", ""))
    elif t == "begin_atomic":
        e = BeginExternalAtomicBlock(rec["block"])
    elif t == "end_atomic":
        e = EndExternalAtomicBlock(rec["block"])
    else:
        e = _EVENT_TYPES[t]()
    return Unique(e, rec["id"])


def _external_to_json(e: ExternalEvent) -> Dict[str, Any]:
    rec: Dict[str, Any] = {"eid": e.eid}
    if e.block_id is not None:
        rec["block"] = e.block_id
    if isinstance(e, Start):
        rec.update(type="start", name=e.name)
    elif isinstance(e, Kill):
        rec.update(type="kill", name=e.name)
    elif isinstance(e, HardKill):
        rec.update(type="hardkill", name=e.name)
    elif isinstance(e, Send):
        rec.update(type="send", name=e.name, msg=_msg_to_json(e.message()))
    elif isinstance(e, WaitQuiescence):
        rec.update(type="wait_quiescence", budget=e.budget)
    elif isinstance(e, WaitCondition) and e.cond_id is not None:
        # The cond_id form is closure-free (names a DSLApp.conditions
        # entry) and round-trips; the host-closure form below does not.
        rec.update(type="wait_condition", cond_id=e.cond_id, budget=e.budget)
    elif isinstance(e, Partition):
        rec.update(type="partition", a=e.a, b=e.b)
    elif isinstance(e, UnPartition):
        rec.update(type="unpartition", a=e.a, b=e.b)
    else:
        raise TypeError(
            f"{type(e).__name__} is not serializable (closure-form "
            "WaitCondition/CodeBlock close over host code; reference "
            "sanitization drops them too)"
        )
    return rec


def _external_from_json(rec: Dict[str, Any], app: Optional[DSLApp]) -> ExternalEvent:
    t = rec["type"]
    if t == "start":
        ctor = None
        if app is not None:
            ctor = dsl_actor_factory(app, app.actor_id(rec["name"]))
        e: ExternalEvent = Start(rec["name"], ctor=ctor)
    elif t == "kill":
        e = Kill(rec["name"])
    elif t == "hardkill":
        e = HardKill(rec["name"])
    elif t == "send":
        msg = _msg_from_json(rec["msg"])
        e = Send(rec["name"], MessageConstructor(lambda m=msg: m))
    elif t == "wait_quiescence":
        e = WaitQuiescence(budget=rec.get("budget"))
    elif t == "wait_condition":
        e = WaitCondition(cond_id=rec["cond_id"], budget=rec.get("budget"))
    elif t == "partition":
        e = Partition(rec["a"], rec["b"])
    elif t == "unpartition":
        e = UnPartition(rec["a"], rec["b"])
    else:
        raise TypeError(f"unknown external record {t!r}")
    # Restore the recorded identity: minimization artifacts reference
    # events by eid (reference: ids preserved via the saved IDGenerator
    # state, Serialization.scala:181-182,318-321). Advance the global
    # counter so fresh events never alias restored ones.
    object.__setattr__(e, "eid", rec["eid"])
    ensure_eid_floor(rec["eid"])
    if rec.get("block") is not None:
        # Block ids ride the eid counter; floor past them too so fresh
        # blocks never alias restored ones.
        object.__setattr__(e, "block_id", rec["block"])
        ensure_eid_floor(rec["block"])
    return e


def _metadata() -> Dict[str, Any]:
    """Reference: src/main/python/lifecycle.py — host/git capture."""
    meta = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "host": platform.node(),
        "platform": platform.platform(),
    }
    try:
        meta["git_sha"] = (
            subprocess.run(
                ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
                timeout=5, cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip()
        )
    except Exception:
        pass
    return meta


class ExperimentSerializer:
    @staticmethod
    def save(
        directory: str,
        externals: Sequence[ExternalEvent],
        trace: EventTrace,
        violation: Any,
        app_name: str = "",
        mcs: Optional[Sequence[ExternalEvent]] = None,
        minimized_trace: Optional[EventTrace] = None,
        stats: Optional[MinimizationStats] = None,
        device_trace=None,  # int32 [rows, rec_width] device records
    ) -> str:
        os.makedirs(directory, exist_ok=True)

        def write(name: str, obj) -> None:
            with open(os.path.join(directory, name), "w") as f:
                json.dump(obj, f, indent=1)

        write("metadata.json", {**_metadata(), "app": app_name})
        write("externals.json", [_external_to_json(e) for e in externals])
        write("event_trace.json", [_event_to_json(u) for u in trace.events])
        if isinstance(violation, IntViolation):
            write(
                "violation.json",
                {"code": violation.code, "nodes": list(violation.nodes)},
            )
        if mcs is not None:
            write("mcs.json", [e.eid for e in mcs])
        if minimized_trace is not None:
            write(
                "minimized_trace.json",
                [_event_to_json(u) for u in minimized_trace.events],
            )
        if stats is not None:
            with open(os.path.join(directory, "minimization_stats.json"), "w") as f:
                f.write(stats.to_json())
        if device_trace is not None:
            from .native import write_record_log

            write_record_log(
                os.path.join(directory, "device_trace.demirec"), device_trace
            )
        return directory


def save_dep_graph(directory: str, tracker) -> str:
    """Persist a DepTracker's happens-before forest (reference: depGraph
    nodes/edges, Serialization.scala:176-187, 391-421) so restartable
    minimization can re-seed DPOR without re-running the recording."""
    os.makedirs(directory, exist_ok=True)
    records = []
    for rec in tracker.to_records():
        rec = dict(rec)
        rec["fp"] = _fp_to_json(rec["fp"])
        records.append(rec)
    path = os.path.join(directory, "dep_graph.json")
    with open(path, "w") as f:
        json.dump(records, f, indent=1)
    return path


def _warn_corrupt(path: str, exc: Exception) -> None:
    """A truncated or unparsable checkpoint behaves like an ABSENT one
    (the --resume run redoes that stage) instead of crashing — but never
    silently: warn + ``persist.stage_corrupt`` (force-written so the
    degradation reaches every snapshot regardless of DEMI_OBS)."""
    import sys

    from . import obs

    obs.counter("persist.stage_corrupt").force_inc()
    print(
        f"demi_tpu: checkpoint {path!r} is corrupt or truncated "
        f"({type(exc).__name__}: {exc}); treating it as absent",
        file=sys.stderr,
    )


def load_dep_graph(directory: str, fingerprinter):
    """Rebuild the DepTracker saved by save_dep_graph; None if absent —
    or corrupt/truncated (warn + counter, treat as absent: a damaged
    artifact must degrade a --resume run, never crash it)."""
    from .schedulers.dep_tracker import DepTracker

    path = os.path.join(directory, "dep_graph.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            records = json.load(f)
        for rec in records:
            rec["fp"] = _fp_from_json(rec["fp"])
        return DepTracker.from_records(records, fingerprinter)
    except Exception as exc:
        _warn_corrupt(path, exc)
        return None


def save_stage(
    directory: str,
    stage: str,
    externals: Sequence[ExternalEvent],
    trace: EventTrace,
) -> None:
    """Checkpoint one minimization-pipeline stage's outputs (reference:
    every gamut stage's trace is serialized for restart,
    RunnerUtils.scala:171-500 + deserializeExperiment:502-525)."""
    os.makedirs(directory, exist_ok=True)
    obj = {
        "stage": stage,
        "externals": [_external_to_json(e) for e in externals],
        "trace": [_event_to_json(u) for u in trace.events],
    }
    with open(os.path.join(directory, f"stage_{stage}.json"), "w") as f:
        json.dump(obj, f, indent=1)


def load_stage(directory: str, stage: str, app: Optional[DSLApp] = None):
    """(externals, trace) for a checkpointed stage, or None if absent —
    or truncated/unparsable (warn + counter, treat as absent so a
    --resume run redoes the stage instead of crashing)."""
    path = os.path.join(directory, f"stage_{stage}.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            obj = json.load(f)
        externals = [_external_from_json(r, app) for r in obj["externals"]]
        events = [_event_from_json(r, app) for r in obj["trace"]]
        return externals, EventTrace(events, externals)
    except Exception as exc:
        _warn_corrupt(path, exc)
        return None


class ExperimentDeserializer:
    def __init__(self, directory: str, app: Optional[DSLApp] = None):
        self.directory = directory
        self.app = app

    def _read(self, name: str, required: bool = False):
        path = os.path.join(self.directory, name)
        if not os.path.exists(path):
            if required:
                raise FileNotFoundError(
                    f"not an experiment dir: {self.directory!r} has no {name}"
                )
            return None
        with open(path) as f:
            return json.load(f)

    def get_externals(self) -> List[ExternalEvent]:
        return [
            _external_from_json(r, self.app)
            for r in self._read("externals.json", required=True)
        ]

    def get_trace(self, externals: Optional[Sequence[ExternalEvent]] = None) -> EventTrace:
        events = [
            _event_from_json(r, self.app)
            for r in self._read("event_trace.json", required=True)
        ]
        return EventTrace(events, list(externals) if externals else None)

    def get_violation(self) -> Optional[IntViolation]:
        rec = self._read("violation.json")
        if rec is None:
            return None
        return IntViolation(rec["code"], tuple(rec["nodes"]))

    def get_mcs(self, externals: Sequence[ExternalEvent]) -> Optional[List[ExternalEvent]]:
        eids = self._read("mcs.json")
        if eids is None:
            return None
        by_eid = {e.eid: e for e in externals}
        return [by_eid[i] for i in eids]

    def get_stats(self) -> Optional[MinimizationStats]:
        path = os.path.join(self.directory, "minimization_stats.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return MinimizationStats.from_json(f.read())

    def get_device_trace(self):
        path = os.path.join(self.directory, "device_trace.demirec")
        if not os.path.exists(path):
            return None
        from .native import read_record_log

        return read_record_log(path)
