from .common import make_host_invariant, dsl_start_events, DSLSendGenerator

__all__ = ["make_host_invariant", "dsl_start_events", "DSLSendGenerator"]
