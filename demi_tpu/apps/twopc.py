"""Two-phase commit: the transaction-atomicity fixture.

A classic distributed-commit protocol as a fourth app family alongside
broadcast/raft/spark (standing in for the reference's out-of-repo
demi-applications suite, SURVEY.md §4). Actor 0 is the coordinator; the
rest are participants.

Protocol: an external ``BEGIN(txn)`` starts a round — the coordinator
broadcasts ``PREPARE(txn)``; each participant either vetoes (votes no and
aborts locally — a no-voter may abort unilaterally) or becomes prepared
and votes yes; on all-yes the coordinator decides commit, on any no it
decides abort, and broadcasts ``DECIDE``; a coordinator timeout during
collection decides abort (the presumed-abort rule). The veto rule is
deterministic — participant txn % n vetoes txn (txn % n == 0 names the
coordinator, i.e. nobody: that txn commits cleanly) — so fuzzed runs mix
clean commits and vetoed rounds.

Safety invariant (code 1, atomicity): no two alive nodes may finalize the
SAME txn differently (one committed, one aborted).

Seeded bug ``bug="presume_commit"``: the coordinator's collection timeout
presumes commit instead of abort. A schedule that delivers the timeout
before a veto's no-vote commits the fast voters while the vetoing
participant has already aborted — atomicity violated. Needs the timeout
racing the vote messages: a scheduler-controlled interleaving bug in the
reference's style (timers are just deliverable events, WeaveActor.aj's
timer conversion).
"""

from __future__ import annotations

import random as _random
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dsl import DSLApp, vset
from .common import DSLSendGenerator

T_BEGIN = 1  # (tag, txn, 0) external -> coordinator
T_PREPARE = 2  # (tag, txn, 0) coordinator -> participants
T_VOTE = 3  # (tag, txn, yes) participant -> coordinator
T_DECIDE = 4  # (tag, txn, commit) coordinator -> participants
T_TIMEOUT = 5  # coordinator self-timer

MSG_W = 3

# State layout (shared by coordinator and participants).
STATUS = 0  # 0 idle, 1 prepared, 2 committed, 3 aborted
TXN = 1  # txn the STATUS refers to (-1 none)
YES = 2  # coordinator: yes-vote bitmask for the collecting txn
PHASE = 3  # coordinator: 0 idle, 1 collecting

IDLE, PREPARED, COMMITTED, ABORTED = 0, 1, 2, 3


def make_twopc_app(
    num_actors: int, bug: Optional[str] = None, name: str = "t"
) -> DSLApp:
    n = num_actors
    assert n >= 3, "2PC fixture needs a coordinator + >=2 participants"
    state_width = 4
    max_outbox = n  # broadcast to participants + self-timer re-arm
    part_mask = ((1 << n) - 1) & ~1  # participants = actors 1..n-1

    def init_state(actor_id: int) -> np.ndarray:
        s = np.zeros(state_width, np.int32)
        s[TXN] = -1
        return s

    def initial_msgs(actor_id: int) -> np.ndarray:
        rows = np.zeros((1, 2 + MSG_W), np.int32)
        if actor_id == 0:  # coordinator arms its collection timeout
            rows[0, 0] = 1
            rows[0, 1] = 0
            rows[0, 2] = T_TIMEOUT
        return rows

    def _broadcast(tag, txn, flag):
        dsts = jnp.arange(n, dtype=jnp.int32)
        valid = (dsts != 0).astype(jnp.int32)
        zeros = jnp.zeros(n, jnp.int32)
        return jnp.stack(
            [valid, dsts, zeros + tag, zeros + txn, zeros + flag], axis=1
        )

    def _rearm(out):
        row = jnp.stack(
            [jnp.int32(1), jnp.int32(0), jnp.int32(T_TIMEOUT), jnp.int32(0),
             jnp.int32(0)]
        )
        return jnp.where(jnp.arange(n)[:, None] == 0, row[None, :], out)

    def empty_out():
        return jnp.zeros((max_outbox, 2 + MSG_W), jnp.int32)

    def _veto(pid, txn):
        # txn % n picks the vetoing participant (txn % n == 0 names the
        # coordinator, i.e. nobody: that txn can commit cleanly).
        return (txn % n) == pid

    def on_begin(actor_id, state, snd, msg):
        txn = msg[1]
        is_coord = actor_id == 0
        fresh = is_coord & (state[PHASE] == 0)
        state = vset(state, PHASE, 1, fresh)
        state = vset(state, TXN, txn, fresh)
        state = vset(state, YES, 0, fresh)
        state = vset(state, STATUS, IDLE, fresh)
        out = jnp.where(fresh, _broadcast(T_PREPARE, txn, 0), empty_out())
        return state, out

    def on_prepare(actor_id, state, snd, msg):
        txn = msg[1]
        is_part = actor_id != 0
        veto = _veto(actor_id, txn)
        state = vset(state, TXN, txn, is_part)
        state = vset(
            state, STATUS, jnp.where(veto, ABORTED, PREPARED), is_part
        )
        row = jnp.stack(
            [jnp.int32(1), jnp.int32(0), jnp.int32(T_VOTE), txn,
             (~veto).astype(jnp.int32)]
        )
        out = jnp.where(
            is_part & (jnp.arange(n)[:, None] == 0), row[None, :], empty_out()
        )
        return state, out

    def on_vote(actor_id, state, snd, msg):
        txn, yes = msg[1], msg[2]
        is_coord = actor_id == 0
        relevant = is_coord & (state[PHASE] == 1) & (txn == state[TXN])
        no_vote = relevant & (yes == 0)
        yes_mask = jnp.where(
            relevant & (yes != 0), state[YES] | (jnp.int32(1) << snd),
            state[YES],
        )
        state = vset(state, YES, yes_mask)
        all_yes = relevant & (yes_mask == part_mask)
        decide = all_yes | no_vote
        commit = all_yes & ~no_vote
        state = vset(state, PHASE, 0, decide)
        state = vset(
            state, STATUS, jnp.where(commit, COMMITTED, ABORTED), decide
        )
        out = jnp.where(
            decide,
            _broadcast(T_DECIDE, txn, commit.astype(jnp.int32)),
            empty_out(),
        )
        return state, out

    def on_decide(actor_id, state, snd, msg):
        txn, commit = msg[1], msg[2]
        is_part = actor_id != 0
        # A participant that vetoed already aborted unilaterally; a late
        # DECIDE for the same txn must not overwrite it (and can't
        # disagree under the correct protocol).
        relevant = is_part & (txn == state[TXN]) & (state[STATUS] == PREPARED)
        state = vset(
            state, STATUS,
            jnp.where(commit != 0, COMMITTED, ABORTED), relevant,
        )
        return state, empty_out()

    def on_timeout(actor_id, state, snd, msg):
        is_coord = actor_id == 0
        collecting = is_coord & (state[PHASE] == 1)
        txn = state[TXN]
        if bug == "presume_commit":
            # BUG: the collection timeout presumes commit. Racing the
            # timeout ahead of a pending no-vote commits the yes-voters
            # while the vetoing participant already aborted.
            decision = jnp.int32(1)
            final = COMMITTED
        else:
            # Presumed abort: a timed-out collection aborts.
            decision = jnp.int32(0)
            final = ABORTED
        state = vset(state, PHASE, 0, collecting)
        state = vset(state, STATUS, final, collecting)
        out = jnp.where(
            collecting, _broadcast(T_DECIDE, txn, decision), empty_out()
        )
        # Re-arm the self-timer (row 0 is free: broadcasts never target the
        # coordinator). Timers only ever live at actor 0.
        out = jnp.where(is_coord, _rearm(out), empty_out())
        return state, out

    def handler(actor_id, state, snd, msg):
        tag = jnp.clip(msg[0], 1, 5) - 1
        return jax.lax.switch(
            tag, [on_begin, on_prepare, on_vote, on_decide, on_timeout],
            actor_id, state, snd, msg,
        )

    def invariant(states, alive):
        """Atomicity: same txn finalized differently on two alive nodes."""
        status = states[:, STATUS]
        txn = states[:, TXN]
        both = alive[:, None] & alive[None, :]
        same_txn = (txn[:, None] == txn[None, :]) & (txn[:, None] >= 0)
        split = (
            (status[:, None] == COMMITTED) & (status[None, :] == ABORTED)
        )
        return jnp.where(
            jnp.any(both & same_txn & split), jnp.int32(1), jnp.int32(0)
        )

    return DSLApp(
        name=name,
        num_actors=n,
        state_width=state_width,
        msg_width=MSG_W,
        max_outbox=max_outbox,
        init_state=init_state,
        initial_msgs=initial_msgs,
        handler=handler,
        invariant=invariant,
        timer_tags=(T_TIMEOUT,),
        tag_names=("", "Begin", "Prepare", "Vote", "Decide", "Timeout"),
    )


def twopc_send_generator(app: DSLApp) -> DSLSendGenerator:
    """External BEGINs with increasing txn ids (wrong-recipient BEGINs are
    ignored by participants, like the spark generator's submits)."""

    def make_msg(rng: _random.Random, counter: int):
        if counter > 5:
            return None
        return (T_BEGIN, counter, 0)

    return DSLSendGenerator(app, make_msg)
