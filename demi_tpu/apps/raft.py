"""Raft in the dual host/device DSL: the flagship fixture.

Stands in for the reference's out-of-repo akka-raft case studies
(README.md:16, tools/rerun_experiments.sh:7 — branches raft-45..raft-66;
BASELINE.json configs 1-3). A full Raft: leader election, log replication
with conflict truncation, commit advancement — written as one jax-traceable
handler so the same definition drives the host oracle and the vmapped
device kernels.

Timer model (the reference's, WeaveActor.aj:234-335): timers are
scheduler-controlled events, not clocks. The election timer is an
always-available "timeout may fire now" self-event; delivering it consumes
it and the handler re-arms. Arbitrary timing = the scheduler's choice of
when to deliver; reset-on-heartbeat is deliberately not modeled (the
scheduler already controls timing adversarially).

Safety invariants (jitted, checked per-delivery via invariant_interval=1):
  code 1 — Election Safety: two alive leaders in the same term.
  code 2 — committed-prefix agreement: two alive nodes disagree on an
           entry both consider committed.

Seeded bugs for fuzzing (reference-style known-bug case studies, standing
in for the akka-raft raft-NN branches):
  bug="multivote"   — voted_for ignored: a node votes for every candidate
                      of the current term (voter-side two-leaders bug).
  bug="stale_vote"  — candidate counts VoteReply messages from its *older*
                      candidacies (term check missing on the tally):
                      delayed replies from term T-1 elect it in term T
                      without a real majority (candidate-side two-leaders
                      bug; needs message delay/reordering to trigger).
  bug="stale_commit"— leader counts itself twice when advancing commit,
                      committing entries without a true majority.
  bug="gap_append"  — follower drops the Log Matching precheck (prev_idx/
                      prev_term ignored): a reordered AppendEntries writes
                      a later entry over a hole, the leader's match_index
                      advances past the hole, and commit covers an entry
                      the follower never got (committed-prefix violation;
                      raft-56-class, needs message reordering).
  bug="commit_beyond"— follower adopts leader_commit without clamping to
                      its own log length: a heartbeat reordered ahead of
                      its AppendEntries commits an entry the follower
                      doesn't have yet (committed-prefix violation).
  bug="dyn_quorum"  — quorum computed from *discovered* membership (the
                      heard-from bitmask) instead of the configured
                      cluster size: a node electing before any peer
                      exchange sees a 1-node cluster and instantly wins
                      (raft-58-initialization-class bug; two such nodes =
                      two same-term leaders).

One more case study needs NO bug flag: this fixture keeps voted_for/term
in memory only (the DSL has no durable storage), so HardKill+restart wipes
them and a restarted voter can grant a second vote in a term it already
voted in — two same-term leaders (raft-66-class lost-durability bug;
tests/test_raft_case_studies.py::test_lost_vote_durability_on_crash_recovery,
found by crash-recovery fuzzing with bounded WaitQuiescence budgets).
"""

from __future__ import annotations

import random as _random
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..dsl import DSLApp, row_set, seg_set, vgather, vget, vset
from .common import DSLSendGenerator

# Message tags.
T_ELECTION = 1  # timer
T_HEARTBEAT = 2  # timer
T_REQ_VOTE = 3  # (tag, term, last_log_idx, last_log_term)
T_VOTE_REPLY = 4  # (tag, term, granted)
T_APPEND = 5  # (tag, term, prev_idx, prev_term, leader_commit, ent_term, ent_val)
T_APPEND_REPLY = 6  # (tag, term, success, match_idx)
T_CLIENT = 7  # (tag, 0, value)

MSG_W = 7

# Roles.
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

# State layout.
ROLE = 0
TERM = 1
VOTED_FOR = 2  # -1 = none
VOTES = 3  # bitmask of granted votes (candidate)
LOG_LEN = 4
COMMIT = 5  # index of highest committed entry, -1 = none
LEADER_HINT = 6  # believed current leader (-1 unknown) for client routing
LOG_START = 7  # LOG_CAP x (term, value) interleaved


def _edit_refactor(fn):
    """Behavior- and effect-identical rewrite of one handler branch: a
    plain delegating wrapper. The branch's code digest moves; its
    read/write field sets do not — the differential explorer's
    happy path (cone = the edited tag only)."""

    def branch(actor_id, state, snd, msg):
        return fn(actor_id, state, snd, msg)

    return branch


def _edit_opaque(fn):
    """An edit the static effects analyzer cannot see through: a
    ``while`` loop makes the AST interpreter bail, degrading the app's
    effects to unknown (the differential explorer must then fall back
    to full re-exploration). The loop body runs exactly once, so the
    branch stays JAX-traceable and behavior-identical."""

    def branch(actor_id, state, snd, msg):
        first = True
        while first:
            first = False
            out = fn(actor_id, state, snd, msg)
        return out

    return branch


_EDIT_WRAPPERS = {"refactor": _edit_refactor, "opaque": _edit_opaque}

_EDIT_TAGS = {
    "election": T_ELECTION,
    "heartbeat": T_HEARTBEAT,
    "request_vote": T_REQ_VOTE,
    "vote_reply": T_VOTE_REPLY,
    "append": T_APPEND,
    "append_reply": T_APPEND_REPLY,
    "client": T_CLIENT,
}


def _parse_handler_edit(spec: str):
    """``"refactor"`` / ``"opaque"`` with an optional ``:tag`` suffix
    (name or 1-based tag int; default: the RequestVote tag, whose
    field sets the static analyzer fully resolves)."""
    kind, _, target = str(spec).partition(":")
    if kind not in _EDIT_WRAPPERS:
        raise ValueError(f"unknown handler_edit kind {kind!r}")
    tag = T_REQ_VOTE
    if target:
        tag = _EDIT_TAGS.get(target) or int(target)
    if not 1 <= tag <= 7:
        raise ValueError(f"handler_edit tag {tag} out of range 1..7")
    return _EDIT_WRAPPERS[kind], tag


def state_width(n: int, log_cap: int) -> int:
    # + next_index[n] + match_index[n] + heard-from bitmask
    return LOG_START + 2 * log_cap + 2 * n + 1


def make_raft_app(
    num_actors: int,
    log_cap: int = 8,
    bug: Optional[str] = None,
    name: str = "r",
    handler_edit: Optional[str] = None,
) -> DSLApp:
    n = num_actors
    assert n >= 2, "raft fixture requires >= 2 nodes"
    assert n <= 30, "votes bitmask is int32"
    S = state_width(n, log_cap)
    NEXT = LOG_START + 2 * log_cap
    MATCH = NEXT + n
    HEARD = MATCH + n  # bitmask of peers this node has received from
    majority = n // 2 + 1

    def init_state(actor_id: int) -> np.ndarray:
        s = np.zeros(S, np.int32)
        s[VOTED_FOR] = -1
        s[COMMIT] = -1
        s[LEADER_HINT] = -1
        return s

    def initial_msgs(actor_id: int) -> np.ndarray:
        rows = np.zeros((1, 2 + MSG_W), np.int32)
        rows[0, 0] = 1  # valid
        rows[0, 1] = actor_id  # dst = self
        rows[0, 2] = T_ELECTION
        return rows

    # -- helpers (all traced) ---------------------------------------------
    def log_term_at(state, idx):
        """Term of log entry idx; 0 when idx == -1 (empty prefix)."""
        safe = jnp.clip(idx, 0, log_cap - 1)
        t = vget(state, LOG_START + 2 * safe)
        return jnp.where(idx < 0, jnp.int32(0), t)

    def last_log(state):
        lli = state[LOG_LEN] - 1
        return lli, log_term_at(state, lli)

    def empty_outbox():
        return jnp.zeros((n, 2 + MSG_W), jnp.int32)

    def broadcast(actor_id, tag, term, a=0, b=0, c=0, d=0, e=0):
        """Rows sending (tag,...) to every other node."""
        dsts = jnp.arange(n, dtype=jnp.int32)
        valid = (dsts != actor_id).astype(jnp.int32)
        zeros = jnp.zeros(n, jnp.int32)
        return jnp.stack(
            [valid, dsts, zeros + tag, zeros + term, zeros + a, zeros + b,
             zeros + c, zeros + d, zeros + e],
            axis=1,
        )

    def one_row(outbox, slot, dst, tag, term, a=0, b=0, c=0, d=0, e=0, valid=True):
        row = jnp.stack(
            [jnp.asarray(valid, jnp.int32), dst, tag, term, a, b, c, d, e]
        ).astype(jnp.int32)
        return row_set(outbox, slot, row, valid)

    def maybe_step_down(state, term):
        """Adopt a newer term as follower (votes + leader hint cleared)."""
        newer = term > state[TERM]
        state = vset(state, TERM, jnp.where(newer, term, state[TERM]))
        state = vset(state, ROLE, jnp.where(newer, FOLLOWER, state[ROLE]))
        state = vset(state, VOTED_FOR, jnp.where(newer, -1, state[VOTED_FOR]))
        state = vset(state, VOTES, jnp.where(newer, 0, state[VOTES]))
        state = vset(state, LEADER_HINT, jnp.where(newer, -1, state[LEADER_HINT]))
        return state

    def heartbeat_rows(actor_id, state):
        """AppendEntries to every follower: the entry at next_index[i] when
        one exists, else an empty heartbeat. One entry per message (bounded
        payloads; SURVEY.md §7.3)."""
        dsts = jnp.arange(n, dtype=jnp.int32)
        next_idx = state[NEXT : NEXT + n]
        prev_idx = next_idx - 1
        safe_prev = jnp.clip(prev_idx, 0, log_cap - 1)
        prev_term = jnp.where(
            prev_idx < 0, 0, vgather(state, LOG_START + 2 * safe_prev)
        )
        has_entry = next_idx < state[LOG_LEN]
        safe_next = jnp.clip(next_idx, 0, log_cap - 1)
        ent_term = jnp.where(
            has_entry, vgather(state, LOG_START + 2 * safe_next), 0
        )
        ent_val = jnp.where(
            has_entry, vgather(state, LOG_START + 2 * safe_next + 1), 0
        )
        valid = (dsts != actor_id).astype(jnp.int32)
        zeros = jnp.zeros(n, jnp.int32)
        return jnp.stack(
            [valid, dsts, zeros + T_APPEND, zeros + state[TERM], prev_idx,
             prev_term, zeros + state[COMMIT], ent_term, ent_val],
            axis=1,
        )

    # -- per-tag handlers --------------------------------------------------
    def on_election(actor_id, state, snd, msg):
        """Timeout fired: non-leaders start a candidacy; always re-arm."""
        is_leader = state[ROLE] == LEADER
        new_term = state[TERM] + 1
        cand = state
        cand = vset(cand, ROLE, CANDIDATE)
        cand = vset(cand, TERM, new_term)
        cand = vset(cand, VOTED_FOR, actor_id)
        cand = vset(cand, VOTES, jnp.int32(1) << actor_id)
        state = jnp.where(is_leader, state, cand)

        lli, llt = last_log(state)
        rv = broadcast(actor_id, T_REQ_VOTE, state[TERM], a=lli, b=llt)
        out = jnp.where(is_leader, jnp.zeros_like(rv), rv)
        wins_alone = jnp.bool_(False)
        if bug == "dyn_quorum":
            # BUG (raft-58-initialization class): quorum is computed from
            # the nodes this one has *discovered* (heard from), not the
            # configured cluster size. A node whose election timer fires
            # before it has heard from anyone sees a 1-node "cluster",
            # wins its own vote instantly, and two such nodes elect two
            # same-term leaders.
            known = jnp.sum(
                (state[HEARD] >> jnp.arange(n, dtype=jnp.int32)) & 1
            )
            wins_alone = ~is_leader & (1 >= known // 2 + 1)
            state = jnp.where(
                wins_alone, _become_leader(actor_id, state), state
            )
            out = jnp.where(
                wins_alone,
                _arm_heartbeat(actor_id, heartbeat_rows(actor_id, state)),
                out,
            )
        # Re-arm the election timer in the self slot (broadcast never
        # targets self, so that row is free; an instant dyn_quorum winner
        # keeps its heartbeat arm there instead).
        out = one_row(out, actor_id, jnp.int32(actor_id), jnp.int32(T_ELECTION),
                      jnp.int32(0), valid=~wins_alone)
        return state, out

    def _become_leader(actor_id, state):
        st = vset(state, ROLE, LEADER)
        # next_index = log_len for all; match_index self = log_len-1, others -1.
        st = seg_set(st, NEXT, jnp.full((n,), st[LOG_LEN], jnp.int32))
        match = vset(jnp.full((n,), -1, jnp.int32), actor_id, st[LOG_LEN] - 1)
        st = seg_set(st, MATCH, match)
        return st

    def _arm_heartbeat(actor_id, outbox):
        """Overwrite own slot with a heartbeat-timer arm (self row is unused
        by broadcasts, which never target self)."""
        return one_row(outbox, actor_id, jnp.int32(actor_id),
                       jnp.int32(T_HEARTBEAT), jnp.int32(0))

    def on_heartbeat(actor_id, state, snd, msg):
        is_leader = state[ROLE] == LEADER
        out = heartbeat_rows(actor_id, state)
        out = jnp.where(is_leader, out, jnp.zeros_like(out))
        # Re-arm only while leader (a consumed timer of a deposed leader
        # stays dead until re-election arms a fresh one).
        out = jnp.where(is_leader, _arm_heartbeat(actor_id, out), out)
        return state, out

    def on_request_vote(actor_id, state, snd, msg):
        term, lli, llt = msg[1], msg[2], msg[3]
        state = maybe_step_down(state, term)
        my_lli, my_llt = last_log(state)
        log_ok = (llt > my_llt) | ((llt == my_llt) & (lli >= my_lli))
        if bug == "multivote":
            free_vote = jnp.bool_(True)  # BUG: voted_for ignored
        else:
            free_vote = (state[VOTED_FOR] == -1) | (state[VOTED_FOR] == snd)
        grant = (term == state[TERM]) & (state[ROLE] == FOLLOWER) & free_vote & log_ok
        state = vset(state, VOTED_FOR,
            jnp.where(grant, snd, state[VOTED_FOR])
        )
        out = one_row(empty_outbox(), 0, snd, jnp.int32(T_VOTE_REPLY),
                      state[TERM], a=grant.astype(jnp.int32))
        return state, out

    def on_vote_reply(actor_id, state, snd, msg):
        term, granted = msg[1], msg[2]
        state = maybe_step_down(state, term)
        if bug == "stale_vote":
            # BUG: tally ignores which candidacy the reply belongs to.
            count = (state[ROLE] == CANDIDATE) & (granted != 0)
        else:
            count = (
                (state[ROLE] == CANDIDATE) & (term == state[TERM]) & (granted != 0)
            )
        votes = jnp.where(
            count, state[VOTES] | (jnp.int32(1) << snd), state[VOTES]
        )
        state = vset(state, VOTES, votes)
        popcount = jnp.sum(
            (votes[None] >> jnp.arange(n, dtype=jnp.int32)) & 1
        )
        wins = count & (popcount >= majority)
        state = jnp.where(wins, _become_leader(actor_id, state), state)
        out = jnp.where(
            wins,
            _arm_heartbeat(actor_id, heartbeat_rows(actor_id, state)),
            empty_outbox(),
        )
        return state, out

    def on_append(actor_id, state, snd, msg):
        term, prev_idx, prev_term, leader_commit, ent_term, ent_val = (
            msg[1], msg[2], msg[3], msg[4], msg[5], msg[6]
        )
        state = maybe_step_down(state, term)
        current = term == state[TERM]
        # A current-term AppendEntries deposes a same-term candidate and
        # names the current leader.
        state = vset(state, ROLE,
            jnp.where(current & (state[ROLE] == CANDIDATE), FOLLOWER, state[ROLE])
        )
        state = vset(state, LEADER_HINT,
            jnp.where(current, snd, state[LEADER_HINT])
        )
        if bug == "gap_append":
            prev_ok = jnp.bool_(True)  # BUG: Log Matching precheck dropped
        else:
            prev_ok = (prev_idx < state[LOG_LEN]) & (
                log_term_at(state, prev_idx) == prev_term
            )
        ok = current & prev_ok
        has_entry = ent_term != 0
        write_idx = prev_idx + 1
        can_write = ok & has_entry & (write_idx < log_cap)
        # Raft truncation rule (evaluated BEFORE the write): only a
        # *conflicting* existing entry (same index, different term)
        # truncates the suffix; a same-term existing entry is identical
        # (Log Matching) so the longer log is kept, and plain heartbeats
        # never truncate.
        had_existing = write_idx < state[LOG_LEN]
        existing_term = log_term_at(state, write_idx)
        conflict = had_existing & (existing_term != ent_term)
        safe_w = jnp.clip(write_idx, 0, log_cap - 1)
        state = vset(state, LOG_START + 2 * safe_w, ent_term, can_write)
        state = vset(state, LOG_START + 2 * safe_w + 1, ent_val, can_write)
        state = vset(state, LOG_LEN,
            jnp.where(
                can_write,
                jnp.where(conflict | ~had_existing, write_idx + 1, state[LOG_LEN]),
                state[LOG_LEN],
            )
        )
        if bug == "commit_beyond":
            # BUG: commit adopted from any current-term leader message,
            # before the Log Matching check and unclamped — commits entries
            # this follower hasn't received.
            new_commit = jnp.where(
                current, jnp.maximum(state[COMMIT], leader_commit), state[COMMIT]
            )
        else:
            new_commit = jnp.where(
                ok,
                jnp.maximum(state[COMMIT],
                            jnp.minimum(leader_commit, state[LOG_LEN] - 1)),
                state[COMMIT],
            )
        state = vset(state, COMMIT, new_commit)
        match = jnp.where(ok, jnp.where(has_entry & can_write, write_idx, prev_idx), -1)
        out = one_row(empty_outbox(), 0, snd, jnp.int32(T_APPEND_REPLY),
                      state[TERM], a=ok.astype(jnp.int32), b=match)
        return state, out

    def on_append_reply(actor_id, state, snd, msg):
        term, success, match_idx = msg[1], msg[2], msg[3]
        state = maybe_step_down(state, term)
        relevant = (state[ROLE] == LEADER) & (term == state[TERM])
        nexts = state[NEXT : NEXT + n]
        matches = state[MATCH : MATCH + n]
        ok = relevant & (success != 0)
        fail = relevant & (success == 0)
        prev_match = vget(matches, snd)
        new_match = jnp.maximum(prev_match, match_idx)
        matches = vset(matches, snd, new_match, ok)
        nexts = vset(
            nexts, snd,
            jnp.where(ok, new_match + 1, jnp.maximum(vget(nexts, snd) - 1, 0)),
        )
        nexts = jnp.where(relevant, nexts, state[NEXT : NEXT + n])
        state = seg_set(state, NEXT, nexts)
        state = seg_set(state, MATCH, matches)
        # Commit advancement: highest i with log_term[i]==term replicated on
        # a majority. (bug="stale_commit": self counted twice.)
        matches = vset(matches, actor_id, state[LOG_LEN] - 1)
        idxs = jnp.arange(log_cap, dtype=jnp.int32)
        terms = state[LOG_START : LOG_START + 2 * log_cap].reshape(
            log_cap, 2
        )[:, 0]
        repl_count = jnp.sum(
            (matches[None, :] >= idxs[:, None]).astype(jnp.int32), axis=1
        )
        if bug == "stale_commit":
            repl_count = repl_count + 1  # BUG: leader double-counted
        committable = (
            (idxs < state[LOG_LEN])
            & (terms == state[TERM])
            & (repl_count >= majority)
        )
        best = jnp.max(jnp.where(committable, idxs, -1))
        state = vset(state, COMMIT,
            jnp.where(relevant, jnp.maximum(state[COMMIT], best), state[COMMIT])
        )
        return state, empty_outbox()

    def on_client(actor_id, state, snd, msg):
        value = msg[2]
        can = (state[ROLE] == LEADER) & (state[LOG_LEN] < log_cap)
        idx = jnp.clip(state[LOG_LEN], 0, log_cap - 1)
        state = vset(state, LOG_START + 2 * idx, state[TERM], can)
        state = vset(state, LOG_START + 2 * idx + 1, value, can)
        state = vset(state, LOG_LEN,
            jnp.where(can, state[LOG_LEN] + 1, state[LOG_LEN])
        )
        # Leader's own match_index tracks its log.
        state = vset(state, MATCH + actor_id, state[LOG_LEN] - 1, can)
        # Replicate eagerly (standard Raft): AppendEntries go out on append,
        # not only on the next heartbeat timer.
        out = jnp.where(
            can, heartbeat_rows(actor_id, state), empty_outbox()
        )
        # Non-leaders forward the command to their believed leader
        # (standard client routing; forwarded copies are ordinary messages
        # the scheduler may still drop/delay/reorder).
        hint = state[LEADER_HINT]
        fwd = (state[ROLE] != LEADER) & (hint >= 0) & (hint != actor_id)
        out = one_row(
            out, 0, jnp.clip(hint, 0, n - 1), jnp.int32(T_CLIENT),
            jnp.int32(0), a=value, valid=fwd,
        )
        return state, out

    # Branch table built at make-scope (a closure cell of ``handler``)
    # so ``handler_edit`` can swap an entry, and so a per-branch edit
    # moves ``handler_fingerprint`` without touching the shared
    # dispatch prologue's digest.
    branches = [
        on_election, on_heartbeat, on_request_vote, on_vote_reply,
        on_append, on_append_reply, on_client,
    ]
    if handler_edit:
        wrap, edit_tag = _parse_handler_edit(handler_edit)
        branches[edit_tag - 1] = wrap(branches[edit_tag - 1])

    def handler(actor_id, state, snd, msg):
        # Membership discovery: remember every peer we've received from
        # (self counts; external/timer senders are masked off). Only the
        # dyn_quorum bug *reads* this, but it is tracked unconditionally so
        # the layout doesn't depend on the bug flag.
        peer_bit = jnp.where(
            (snd >= 0) & (snd < n), jnp.int32(1) << jnp.clip(snd, 0, n - 1), 0
        )
        state = vset(
            state, HEARD,
            state[HEARD] | peer_bit | (jnp.int32(1) << actor_id),
        )
        tag = jnp.clip(msg[0], 1, 7) - 1
        return jax.lax.switch(
            tag, branches, actor_id, state, snd, msg
        )

    # -- invariants --------------------------------------------------------
    def invariant(states, alive):
        roles = states[:, ROLE]
        terms = states[:, TERM]
        both = alive[:, None] & alive[None, :] & ~jnp.eye(n, dtype=bool)
        two_leaders = jnp.any(
            both
            & (roles[:, None] == LEADER)
            & (roles[None, :] == LEADER)
            & (terms[:, None] == terms[None, :])
        )
        # Committed-prefix agreement.
        idxs = jnp.arange(log_cap, dtype=jnp.int32)
        logs = states[:, LOG_START : LOG_START + 2 * log_cap].reshape(n, log_cap, 2)
        commits = states[:, COMMIT]
        pair_commit = jnp.minimum(commits[:, None], commits[None, :])  # [n, n]
        in_prefix = idxs[None, None, :] <= pair_commit[:, :, None]  # [n, n, cap]
        differs = jnp.any(logs[:, None] != logs[None, :], axis=-1)  # [n, n, cap]
        log_mismatch = jnp.any(both[:, :, None] & in_prefix & differs)
        return jnp.where(
            two_leaders, jnp.int32(1), jnp.where(log_mismatch, jnp.int32(2), 0)
        )

    return DSLApp(
        name=name,
        num_actors=n,
        state_width=S,
        msg_width=MSG_W,
        max_outbox=n,
        init_state=init_state,
        handler=handler,
        initial_msgs=initial_msgs,
        invariant=invariant,
        timer_tags=(T_ELECTION, T_HEARTBEAT),
        tag_names=("", "ElectionTimeout", "HeartbeatTimer", "RequestVote",
                   "VoteReply", "AppendEntries", "AppendReply", "ClientCmd"),
    )


def raft_send_generator(app: DSLApp) -> DSLSendGenerator:
    """External client commands with distinct values."""

    def make_msg(rng: _random.Random, counter: int):
        return (T_CLIENT, 0, counter) + (0,) * (MSG_W - 3)

    return DSLSendGenerator(app, make_msg)
