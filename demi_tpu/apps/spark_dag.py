"""Mini DAG scheduler: the Spark-class fixture.

Stands in for the reference's Spark case study (BASELINE.json config 4:
"Spark DAGScheduler fuzz, job-completion invariant"; demi-applications
spark branch). Actor 0 is the master (DAGScheduler); the rest are workers.
A job is S stages of T tasks; the master launches each task twice
(speculative execution, as Spark does) and advances to the next stage when
the current stage's mask completes; after the last stage it declares the
job done.

Safety invariant (code 1): job_done ⇒ every task the master credited was
actually executed by some worker — masters must not credit work nobody did.

Seeded bug ``bug="stale_task"``: the master ignores the stage field of
TASK_DONE and credits late/duplicate completions from earlier stages to the
*current* stage (the missing-epoch-check bug class the reference's Spark
study targets), so speculative duplicates from stage s complete stage s+1
without its tasks ever running.
"""

from __future__ import annotations

import random as _random
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dsl import DSLApp, row_set, vget, vset
from .common import DSLSendGenerator

T_SUBMIT = 1
T_LAUNCH = 2  # (tag, stage, task)
T_DONE = 3  # (tag, stage, task)

MSG_W = 3

# Master state layout: [current_stage, job_done, credited_mask[stage 0..S-1]]
CUR = 0
DONE_FLAG = 1
MASKS = 2
# Worker state layout: [_, _, executed_mask[stage 0..S-1]] (same width).


def make_spark_app(
    num_workers: int,
    num_stages: int = 2,
    tasks_per_stage: int = 4,
    bug: Optional[str] = None,
    name: str = "s",
) -> DSLApp:
    n = num_workers + 1  # + master (actor 0)
    S = num_stages
    T = tasks_per_stage
    state_width = MASKS + S
    full_mask = (1 << T) - 1
    max_outbox = 2 * T + 1

    def init_state(actor_id: int) -> np.ndarray:
        return np.zeros(state_width, np.int32)

    def _launch_rows(actor_id, stage):
        """Master launches all tasks of ``stage`` twice (speculative)."""
        k = max_outbox
        rows_task = jnp.arange(k, dtype=jnp.int32) % jnp.int32(max(T, 1))
        copy = (jnp.arange(k, dtype=jnp.int32) >= T).astype(jnp.int32)
        valid = (jnp.arange(k) < 2 * T).astype(jnp.int32)
        worker = 1 + (rows_task + copy) % jnp.int32(num_workers)
        zeros = jnp.zeros(k, jnp.int32)
        return jnp.stack(
            [valid, worker, zeros + T_LAUNCH, zeros + stage, rows_task],
            axis=1,
        )

    def on_submit(actor_id, state, snd, msg):
        is_master = actor_id == 0
        fresh = state[CUR] == 0
        launch = is_master & fresh & (state[DONE_FLAG] == 0)
        out = _launch_rows(actor_id, jnp.int32(0))
        out = jnp.where(launch, out, jnp.zeros_like(out))
        return state, out

    def on_launch(actor_id, state, snd, msg):
        stage, task = msg[1], msg[2]
        is_worker = actor_id != 0
        safe_stage = jnp.clip(stage, 0, S - 1)
        bit = jnp.where((task >= 0) & (task < T), jnp.int32(1) << task, 0)
        new_mask = vget(state, MASKS + safe_stage) | bit
        state = vset(state, MASKS + safe_stage, new_mask, is_worker)
        out = jnp.zeros((max_outbox, 2 + MSG_W), jnp.int32)
        row = jnp.stack(
            [jnp.int32(1), jnp.int32(0), jnp.int32(T_DONE), stage, task]
        )
        out = row_set(out, 0, jnp.where(is_worker, row, out[0]))
        return state, out

    def on_done(actor_id, state, snd, msg):
        stage, task = msg[1], msg[2]
        is_master = actor_id == 0
        cur = state[CUR]
        running = (state[DONE_FLAG] == 0) & (cur < S)
        if bug == "stale_task":
            # BUG: stage field ignored — late completions credit the
            # current stage.
            relevant = is_master & running
        else:
            relevant = is_master & running & (stage == cur)
        safe_cur = jnp.clip(cur, 0, S - 1)
        bit = jnp.where((task >= 0) & (task < T), jnp.int32(1) << task, 0)
        mask = vget(state, MASKS + safe_cur) | jnp.where(relevant, bit, 0)
        state = vset(state, MASKS + safe_cur, mask)
        stage_complete = relevant & (mask == full_mask)
        next_stage = cur + 1
        state = vset(state, CUR, jnp.where(stage_complete, next_stage, cur))
        job_done = stage_complete & (next_stage >= S)
        state = vset(state, DONE_FLAG,
            jnp.where(job_done, 1, state[DONE_FLAG])
        )
        launch_next = stage_complete & (next_stage < S)
        out = _launch_rows(actor_id, next_stage)
        out = jnp.where(launch_next, out, jnp.zeros_like(out))
        return state, out

    def handler(actor_id, state, snd, msg):
        tag = jnp.clip(msg[0], 1, 3) - 1
        return jax.lax.switch(
            tag, [on_submit, on_launch, on_done], actor_id, state, snd, msg
        )

    def invariant(states, alive):
        """job_done ⇒ every credited task was executed by some worker."""
        master = states[0]
        credited = master[MASKS : MASKS + S]
        executed = states[1:, MASKS : MASKS + S]  # [workers, S]
        executed_union = jnp.bitwise_or.reduce(executed, axis=0)
        phantom = credited & ~executed_union
        bad = (master[DONE_FLAG] == 1) & jnp.any(phantom != 0) & alive[0]
        return jnp.where(bad, jnp.int32(1), jnp.int32(0))

    return DSLApp(
        name=name,
        num_actors=n,
        state_width=state_width,
        msg_width=MSG_W,
        max_outbox=max_outbox,
        init_state=init_state,
        handler=handler,
        invariant=invariant,
        tag_names=("", "SubmitJob", "LaunchTask", "TaskDone"),
    )


def spark_send_generator(app: DSLApp) -> DSLSendGenerator:
    """External SubmitJob to the master."""

    def make_msg(rng: _random.Random, counter: int):
        if counter > 1:
            return None  # one job per program
        return (T_SUBMIT, 0, 0)

    return DSLSendGenerator(app, make_msg)
