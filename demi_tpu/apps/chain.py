"""Chain replication: the storage-protocol fixture.

Fifth app family (with broadcast/raft/spark/twopc, standing in for the
reference's out-of-repo demi-applications suite, SURVEY.md §4). Actors
form a chain head=0 → … → tail=n-1: external WRITEs enter at the head
and replicate down the chain; a version is COMMITTED when it reaches the
tail, which sends an ACK back up — each node's committed watermark only
ever advances via tail-originated ACKs. External READs may hit any node
and are served from the committed watermark.

Safety invariant (code 1, phantom read): no alive node may ever have
SERVED a version newer than the tail's committed version — a served
value that never commits was observed by a client and then lost.

Seeded bug ``bug="read_uncommitted"``: reads are served from the latest
*received* version instead of the committed watermark. Harmless until a
mid-chain Kill strands the write: the head serves v, the replication
dies between head and tail, v never commits — the classic dirty-read
anomaly chain replication's commit rule exists to prevent. Needs
fault injection (Kill) + a read racing the replication: a
scheduler-and-fault bug in the reference's style.
"""

from __future__ import annotations

import random as _random
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..dsl import DSLApp, vset
from .common import DSLSendGenerator

T_WRITE = 1  # (tag, value, 0) external -> head
T_REPL = 2  # (tag, version, value) node i -> i+1
T_ACK = 3  # (tag, version, 0) node i -> i-1 (originates at tail)
T_READ = 4  # (tag, 0, 0) external -> any node

MSG_W = 3

VERSION = 0  # latest version received
VALUE = 1
COMMITTED = 2  # committed watermark (tail-originated)
SERVED = 3  # newest version this node ever served to a read


def make_chain_app(
    num_actors: int, bug: Optional[str] = None, name: str = "c"
) -> DSLApp:
    n = num_actors
    assert n >= 2, "chain needs at least head and tail"
    state_width = 4
    max_outbox = 1

    def init_state(actor_id: int) -> np.ndarray:
        return np.zeros(state_width, np.int32)

    def _one(dst, tag, a, b):
        row = jnp.stack(
            [jnp.int32(1), dst.astype(jnp.int32), tag.astype(jnp.int32),
             a.astype(jnp.int32), b.astype(jnp.int32)]
        )
        return row[None, :]

    def _none():
        return jnp.zeros((max_outbox, 2 + MSG_W), jnp.int32)

    def on_write(actor_id, state, snd, msg):
        value = msg[1]
        is_head = actor_id == 0
        version = state[VERSION] + 1
        state = vset(state, VERSION, version, is_head)
        state = vset(state, VALUE, value, is_head)
        # Single-node chain commits immediately; else replicate to node 1.
        if n == 1:  # pragma: no cover - guarded by assert n >= 2
            return state, _none()
        tail_here = is_head & (n == 1)
        out = jnp.where(
            is_head,
            _one(jnp.int32(1), jnp.int32(T_REPL), version, value),
            _none(),
        )
        return state, out

    def on_repl(actor_id, state, snd, msg):
        version, value = msg[1], msg[2]
        in_chain = actor_id != 0
        newer = version > state[VERSION]
        apply_ = in_chain & newer
        state = vset(state, VERSION, version, apply_)
        state = vset(state, VALUE, value, apply_)
        is_tail = actor_id == n - 1
        # Tail: commit + ack upstream. Middle: forward down the chain.
        state = vset(
            state, COMMITTED,
            jnp.maximum(state[COMMITTED], version), apply_ & is_tail,
        )
        nxt = jnp.clip(actor_id + 1, 0, n - 1)
        prv = jnp.clip(actor_id - 1, 0, n - 1)
        out = jnp.where(
            apply_,
            jnp.where(
                is_tail,
                _one(jnp.asarray(prv), jnp.int32(T_ACK), version, jnp.int32(0)),
                _one(jnp.asarray(nxt), jnp.int32(T_REPL), version, value),
            ),
            _none(),
        )
        return state, out

    def on_ack(actor_id, state, snd, msg):
        version = msg[1]
        state = vset(
            state, COMMITTED, jnp.maximum(state[COMMITTED], version)
        )
        prv = jnp.clip(actor_id - 1, 0, n - 1)
        out = jnp.where(
            actor_id > 0,
            _one(jnp.asarray(prv), jnp.int32(T_ACK), version, jnp.int32(0)),
            _none(),
        )
        return state, out

    def on_read(actor_id, state, snd, msg):
        if bug == "read_uncommitted":
            # BUG: serve the latest received version — observable before
            # it commits, lost if the chain dies mid-replication.
            served = jnp.maximum(state[SERVED], state[VERSION])
        else:
            served = jnp.maximum(state[SERVED], state[COMMITTED])
        state = vset(state, SERVED, served)
        return state, _none()

    def handler(actor_id, state, snd, msg):
        tag = jnp.clip(msg[0], 1, 4) - 1
        return jax.lax.switch(
            tag, [on_write, on_repl, on_ack, on_read],
            actor_id, state, snd, msg,
        )

    def invariant(states, alive):
        """Phantom read: an alive node served a version beyond the alive
        tail's committed watermark."""
        committed_tail = states[n - 1, COMMITTED]
        served = states[:, SERVED]
        bad = jnp.any(alive & (served > committed_tail)) & alive[n - 1]
        return jnp.where(bad, jnp.int32(1), jnp.int32(0))

    return DSLApp(
        name=name,
        num_actors=n,
        state_width=state_width,
        msg_width=MSG_W,
        max_outbox=max_outbox,
        init_state=init_state,
        handler=handler,
        invariant=invariant,
        tag_names=("", "Write", "Repl", "Ack", "Read"),
    )


def chain_send_generator(app: DSLApp) -> DSLSendGenerator:
    """Writes (to whoever — non-heads ignore) interleaved with reads."""

    def make_msg(rng: _random.Random, counter: int):
        if counter > 8:
            return None
        if rng.random() < 0.5:
            return (T_WRITE, 10 + counter, 0)
        return (T_READ, 0, 0)

    return DSLSendGenerator(app, make_msg)
