"""Glue between DSL apps and the host tier: invariant adaptation, Start
prefixes, and fuzzer message generation.

The device tier evaluates ``app.invariant(states, alive)`` directly as a
jitted predicate; here we adapt the same function to the host oracle's
checkpoint-based invariant signature (externals, {name -> CheckpointReply})
(reference signature: TestOracle.scala:27).
"""

from __future__ import annotations

import random as _random
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..dsl import DSLApp
from ..external_events import MessageConstructor, Send, Start
from ..minimization.test_oracle import IntViolation
from ..runtime.actor import dsl_actor_factory

def _jitted_invariant(app: DSLApp):
    # Cached on the app instance (id(app)-keyed globals collide after GC).
    fn = getattr(app, "_jitted_invariant", None)
    if fn is None:
        from ..utils.hostjit import host_jit

        fn = host_jit(app.invariant)
        object.__setattr__(app, "_jitted_invariant", fn)
    return fn


def _jitted_condition(app: DSLApp, cond_id: int):
    """Host evaluation of DSLApp.conditions[cond_id] (WaitCondition's
    dual-tier form); cached per app like the invariant."""
    cache = getattr(app, "_jitted_conditions", None)
    if cache is None:
        cache = {}
        object.__setattr__(app, "_jitted_conditions", cache)
    fn = cache.get(cond_id)
    if fn is None:
        from ..utils.hostjit import host_jit

        fn = cache[cond_id] = host_jit(app.conditions[cond_id])
    return fn


def make_host_invariant(app: DSLApp) -> Callable:
    """Adapt the app's jitted (states, alive) -> int32 predicate to the host
    checkpoint-based invariant. Actors absent/crashed/isolated -> not alive."""
    assert app.invariant is not None

    def invariant(externals, checkpoint) -> Optional[IntViolation]:
        states = np.zeros((app.num_actors, app.state_width), np.int32)
        alive = np.zeros(app.num_actors, bool)
        for i in range(app.num_actors):
            reply = checkpoint.get(app.actor_name(i))
            if reply is not None and reply.data is not None:
                states[i] = np.asarray(reply.data, np.int32)
                alive[i] = True
        code = int(_jitted_invariant(app)(states, alive))
        if code != 0:
            affected = tuple(
                app.actor_name(i) for i in range(app.num_actors) if alive[i]
            )
            return IntViolation(code, affected)
        return None

    return invariant


def dsl_start_events(app: DSLApp) -> List[Start]:
    """Start prefix spawning every actor of the app."""
    return [
        Start(app.actor_name(i), ctor=dsl_actor_factory(app, i))
        for i in range(app.num_actors)
    ]


class DSLSendGenerator:
    """Fuzzer message generator sending app-provided messages to random alive
    actors. ``make_msg(rng, counter) -> tuple`` builds the payload."""

    def __init__(self, app: DSLApp, make_msg: Callable[[_random.Random, int], tuple]):
        self.app = app
        self.make_msg = make_msg
        self._counter = 0

    def reset(self) -> None:
        self._counter = 0

    def generate(self, rng: _random.Random, alive: Sequence[str]) -> Optional[Send]:
        if not alive:
            return None
        self._counter += 1
        msg = self.make_msg(rng, self._counter)
        if msg is None:
            return None
        target = rng.choice(list(alive))
        return Send(target, MessageConstructor(lambda m=msg: m))
