"""Reliable broadcast: the canonical fixture app.

Stands in for the reference's out-of-repo demi-applications test apps
(SURVEY.md §4; BASELINE.json config 5: "synthetic reliable-broadcast,
64 actors"). Protocol: on first receipt of BCAST(id), mark it delivered and
relay it to every other node. Safety invariant (checked at quiescence):
agreement — all alive nodes have delivered the same set.

``reliable=False`` seeds the classic bug: no relay, so killing the
first receiver mid-broadcast strands the message at a subset of nodes.

The handler is jax-traceable and drives both the host oracle and the
device kernels unchanged.
"""

from __future__ import annotations

import random as _random
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..dsl import DSLApp
from .common import DSLSendGenerator

TAG_BCAST = 1
MAX_IDS = 30  # broadcast ids fit one int32 bitmask


def make_broadcast_app(
    num_actors: int, reliable: bool = True, name: str = "n"
) -> DSLApp:
    state_width = 1  # state[0] = bitmask of delivered broadcast ids
    msg_width = 2  # (tag, bcast_id)
    max_outbox = num_actors

    def init_state(actor_id: int) -> np.ndarray:
        return np.zeros(state_width, np.int32)

    def handler(actor_id, state, snd, msg):
        tag, bid = msg[0], msg[1]
        bit = jnp.where(
            (bid >= 0) & (bid < MAX_IDS), jnp.int32(1) << bid, jnp.int32(0)
        )
        already = (state[0] & bit) != 0
        deliver = (tag == TAG_BCAST) & ~already & (bit != 0)
        # Index-free write (width-1 state): keeps the handler free of
        # scatter ops, which have no Mosaic lowering (pallas kernels).
        new_state = jnp.where(deliver, state[0] | bit, state[0])[None]
        dsts = jnp.arange(max_outbox, dtype=jnp.int32)
        if reliable:
            valid = deliver & (dsts != actor_id) & (dsts < num_actors)
        else:
            valid = jnp.zeros_like(dsts, dtype=bool)
        outbox = jnp.stack(
            [
                valid.astype(jnp.int32),
                dsts,
                jnp.full((max_outbox,), TAG_BCAST, jnp.int32),
                jnp.full((max_outbox,), bid, jnp.int32),
            ],
            axis=1,
        )
        return new_state, outbox

    def invariant(states, alive):
        """Agreement: any two alive nodes with different delivered sets is a
        violation (code 1)."""
        masks = states[:, 0]
        disagree = (
            (masks[:, None] != masks[None, :]) & alive[:, None] & alive[None, :]
        )
        return jnp.where(jnp.any(disagree), jnp.int32(1), jnp.int32(0))

    return DSLApp(
        name=name,
        num_actors=num_actors,
        state_width=state_width,
        msg_width=msg_width,
        max_outbox=max_outbox,
        init_state=init_state,
        handler=handler,
        invariant=invariant,
        tag_names=("", "BCAST"),
    )


def broadcast_send_generator(app: DSLApp) -> DSLSendGenerator:
    def make_msg(rng: _random.Random, counter: int) -> Optional[Tuple[int, int]]:
        # Ids must stay distinct within one program (aliased ids would mask
        # stranded broadcasts from the agreement invariant); the generator
        # resets per program, and the fuzzer's futile-guard handles a dry
        # generator gracefully.
        if counter > MAX_IDS:
            return None
        return (TAG_BCAST, counter - 1)

    return DSLSendGenerator(app, make_msg)
